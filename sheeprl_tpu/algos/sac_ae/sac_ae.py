"""SAC-AE — pixel SAC with a convolutional autoencoder.

Behavioral contract from the reference ``sheeprl/algos/sac_ae/sac_ae.py``
(train :46-133, main :136-428): per update one env step, then (every
``update``) a soft-critic update that also trains the encoder; EMA of the
target Q heads (``algo.tau``) and target encoder (``algo.encoder.tau``) every
``critic.target_network_frequency``; actor + alpha updates on *detached*
conv features every ``actor.network_frequency``; an autoencoder update
(5-bit-quantized pixel targets + latent L2 penalty) every
``decoder.update_freq``.

TPU-native design (same chassis as ``sac/sac.py``): ONE jitted ``shard_map``
program scans the G gradient steps; the cadence gates enter as dynamic bools
applied via ``jnp.where`` on parameter/optimizer pytrees, so no cadence ever
recompiles; the twin-Q ensemble is a vmapped stacked-params apply.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.algos.sac.agent import action_bounds, squash_sample
from sheeprl_tpu.algos.sac.loss import critic_loss, entropy_loss, policy_loss
from sheeprl_tpu.algos.sac_ae.agent import build_agent, ensemble_q, preprocess_obs
from sheeprl_tpu.algos.sac_ae.utils import normalize_obs_jnp, prepare_obs, test
from sheeprl_tpu.ckpt import preemption_requested, should_checkpoint, warn_checkpoint_rounding
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.utils.host import HostParamMirror
from sheeprl_tpu.replay import make_replay_buffer
from sheeprl_tpu.data.staging import make_replay_staging
from sheeprl_tpu.envs.rollout import BurstActor
from sheeprl_tpu.envs.vector import make_vector_env
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.obs import (
    learn_probes,
    log_sps_metrics,
    observe_probes,
    probes_enabled,
    profile_tick,
    span,
)
from sheeprl_tpu.obs.dist import pmean
from sheeprl_tpu.utils.optim import clip_norm_of
from sheeprl_tpu.utils.utils import fetch_losses_if_observed, save_configs
from sheeprl_tpu.utils.jax_compat import shard_map

sg = jax.lax.stop_gradient


def build_train_fn(
    encoder,
    decoder,
    qf,
    actor_trunk,
    txs: Dict[str, Any],
    cfg,
    fabric,
    action_scale: np.ndarray,
    action_bias: np.ndarray,
    target_entropy: float,
):
    """``train(state, opts, batch, key, gates) -> (state, opts, metrics)``;
    ``batch`` leaves are ``[G, B_local, ...]``, ``gates`` is a dict of
    dynamic bools {do_ema, do_actor, do_decoder}."""
    gamma = float(cfg.algo.gamma)
    tau = float(cfg.algo.tau)
    encoder_tau = float(cfg.algo.encoder.tau)
    l2_lambda = float(cfg.algo.decoder.l2_lambda)
    n_critics = int(cfg.algo.critic.n)
    axis = fabric.data_axis
    cnn_keys = tuple(cfg.cnn_keys.encoder)
    mlp_keys = tuple(cfg.mlp_keys.encoder)
    cnn_dec_keys = tuple(cfg.cnn_keys.decoder)
    mlp_dec_keys = tuple(cfg.mlp_keys.decoder)
    scale = jnp.asarray(action_scale)
    bias = jnp.asarray(action_bias)
    tgt_entropy = jnp.float32(target_entropy)
    # learning-health probes (obs/learn): build-time gate, zero ops when off
    learn_on = probes_enabled(cfg)
    learn_clips = {
        "critic": clip_norm_of(txs["qf"]),
        "actor": clip_norm_of(txs["actor"]),
        "alpha": clip_norm_of(txs["alpha"]),
        "decoder": clip_norm_of(txs["decoder"]),
    }

    def normalize(batch, prefix=""):
        out = {}
        for k in cnn_keys:
            out[k] = batch[prefix + k] / 255.0
        for k in mlp_keys:
            out[k] = batch[prefix + k]
        return out

    def encode(enc_params, obs, detach_conv=False):
        return encoder.apply({"params": enc_params}, obs, detach_conv)

    def where_tree(flag, a, b):
        return jax.tree_util.tree_map(lambda x, y: jnp.where(flag, x, y), a, b)

    def one_step(carry, batch_and_key):
        state, opts, gates = carry
        batch, key = batch_and_key
        c_key, a_key, d_key = jax.random.split(key, 3)
        obs = normalize(batch)
        next_obs = normalize(batch, "next_")

        # ---- soft critic (trains encoder too; reference train :77-86)
        alpha = sg(jnp.exp(state["log_alpha"]))
        next_feat = encode(state["target_encoder"], next_obs)
        mean, std = actor_trunk.apply({"params": state["actor"]}, encode(state["encoder"], next_obs))
        next_actions, next_logprob = squash_sample(mean, std, c_key, scale, bias)
        target_q = ensemble_q(qf, state["target_qfs"], next_feat, next_actions)
        min_target = jnp.min(target_q, axis=-1, keepdims=True) - alpha * next_logprob
        td_target = sg(batch["rewards"] + (1.0 - batch["dones"]) * gamma * min_target)

        def qf_loss_fn(p):
            feat = encode(p["encoder"], obs)
            q = ensemble_q(qf, p["qfs"], feat, batch["actions"])
            return critic_loss(q, td_target, n_critics)

        qf_loss, qf_grads = jax.value_and_grad(qf_loss_fn)(
            {"encoder": state["encoder"], "qfs": state["qfs"]}
        )
        qf_grads = pmean(qf_grads, axis)
        qf_updates, qf_opt = txs["qf"].update(
            qf_grads, opts["qf"], {"encoder": state["encoder"], "qfs": state["qfs"]}
        )
        new_enc_qfs = optax.apply_updates(
            {"encoder": state["encoder"], "qfs": state["qfs"]}, qf_updates
        )
        enc_params, qfs = new_enc_qfs["encoder"], new_enc_qfs["qfs"]

        # ---- dual-tau target EMA, gated (reference train :89-92)
        target_qfs = where_tree(
            gates["do_ema"],
            jax.tree_util.tree_map(lambda p, t: tau * p + (1 - tau) * t, qfs, state["target_qfs"]),
            state["target_qfs"],
        )
        target_enc = where_tree(
            gates["do_ema"],
            jax.tree_util.tree_map(
                lambda p, t: encoder_tau * p + (1 - encoder_tau) * t,
                enc_params,
                state["target_encoder"],
            ),
            state["target_encoder"],
        )

        # ---- actor + alpha on detached conv features, gated (reference :94-113)
        def actor_loss_fn(actor_params):
            feat = encode(enc_params, obs, detach_conv=True)
            mean, std = actor_trunk.apply({"params": actor_params}, feat)
            actions, logprob = squash_sample(mean, std, a_key, scale, bias)
            q = ensemble_q(qf, qfs, feat, actions)
            min_q = jnp.min(q, axis=-1, keepdims=True)
            return policy_loss(alpha, logprob, min_q), logprob

        (actor_loss, logprob), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(
            state["actor"]
        )
        actor_grads = pmean(actor_grads, axis)
        actor_updates, actor_opt = txs["actor"].update(actor_grads, opts["actor"], state["actor"])
        actor_params = where_tree(
            gates["do_actor"], optax.apply_updates(state["actor"], actor_updates), state["actor"]
        )
        actor_opt = where_tree(gates["do_actor"], actor_opt, opts["actor"])

        def alpha_loss_fn(log_alpha):
            return entropy_loss(log_alpha, sg(logprob), tgt_entropy)

        alpha_loss, alpha_grad = jax.value_and_grad(alpha_loss_fn)(state["log_alpha"])
        alpha_grad = pmean(alpha_grad, axis)
        alpha_updates, alpha_opt = txs["alpha"].update(alpha_grad, opts["alpha"], state["log_alpha"])
        log_alpha = jnp.where(
            gates["do_actor"], optax.apply_updates(state["log_alpha"], alpha_updates), state["log_alpha"]
        )
        alpha_opt = where_tree(gates["do_actor"], alpha_opt, opts["alpha"])

        # ---- autoencoder, gated (reference train :115-131)
        def recon_loss_fn(p):
            hidden = encode(p["encoder"], obs)
            recon = decoder.apply({"params": p["decoder"]}, hidden)
            loss = 0.0
            keys = jax.random.split(d_key, max(len(cnn_dec_keys), 1))
            for i, k in enumerate(cnn_dec_keys):
                target = preprocess_obs(batch[k], bits=5, key=keys[i])
                loss += jnp.mean((target - recon[k]) ** 2) + l2_lambda * jnp.mean(
                    0.5 * jnp.sum(hidden**2, -1)
                )
            for k in mlp_dec_keys:
                loss += jnp.mean((batch[k] - recon[k]) ** 2) + l2_lambda * jnp.mean(
                    0.5 * jnp.sum(hidden**2, -1)
                )
            return loss

        recon_loss, recon_grads = jax.value_and_grad(recon_loss_fn)(
            {"encoder": enc_params, "decoder": state["decoder"]}
        )
        recon_grads = pmean(recon_grads, axis)
        enc_updates, enc_opt = txs["encoder"].update(
            recon_grads["encoder"], opts["encoder"], enc_params
        )
        dec_updates, dec_opt = txs["decoder"].update(
            recon_grads["decoder"], opts["decoder"], state["decoder"]
        )
        enc_params = where_tree(
            gates["do_decoder"], optax.apply_updates(enc_params, enc_updates), enc_params
        )
        dec_params = where_tree(
            gates["do_decoder"],
            optax.apply_updates(state["decoder"], dec_updates),
            state["decoder"],
        )
        enc_opt = where_tree(gates["do_decoder"], enc_opt, opts["encoder"])
        dec_opt = where_tree(gates["do_decoder"], dec_opt, opts["decoder"])

        new_state = {
            "encoder": enc_params,
            "target_encoder": target_enc,
            "qfs": qfs,
            "target_qfs": target_qfs,
            "actor": actor_params,
            "decoder": dec_params,
            "log_alpha": log_alpha,
        }
        new_opts = {
            "qf": qf_opt,
            "actor": actor_opt,
            "alpha": alpha_opt,
            "encoder": enc_opt,
            "decoder": dec_opt,
        }
        metrics = jnp.stack([qf_loss, actor_loss, alpha_loss, recon_loss])
        if learn_on:
            probes = learn_probes(
                {
                    "critic": qf_grads,
                    "actor": actor_grads,
                    "alpha": alpha_grad,
                    "decoder": recon_grads,
                },
                params={
                    "critic": {"encoder": state["encoder"], "qfs": state["qfs"]},
                    "actor": state["actor"],
                    "alpha": state["log_alpha"],
                    "decoder": state["decoder"],
                },
                updates={
                    "critic": qf_updates,
                    "actor": actor_updates,
                    "alpha": alpha_updates,
                    "decoder": dec_updates,
                },
                losses=(qf_loss, actor_loss, alpha_loss, recon_loss),
                clip_norms=learn_clips,
            )
            return (new_state, new_opts, gates), (metrics, probes)
        return (new_state, new_opts, gates), metrics

    def local_train(state, opts, batch, key, gates):
        g = jax.tree_util.tree_leaves(batch)[0].shape[0]
        keys = jax.random.split(key, g)
        (state, opts, _), ys = jax.lax.scan(one_step, (state, opts, gates), (batch, keys))
        metrics, probes = ys if learn_on else (ys, None)
        metrics = pmean(jnp.mean(metrics, axis=0), axis)
        if learn_on:
            return state, opts, metrics, probes
        return state, opts, metrics

    shmapped = shard_map(
        local_train,
        mesh=fabric.mesh,
        in_specs=(P(), P(), P(None, axis), P(), P()),
        out_specs=(P(), P(), P()) + ((P(),) if learn_on else ()),
        check_vma=False,
    )
    return jax.jit(shmapped, donate_argnums=(0, 1))


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    if "minedojo" in (cfg.env.wrapper._target_ or "").lower():
        raise ValueError("MineDojo is not currently supported by SAC-AE agent")

    world_size = fabric.world_size
    root_key = fabric.seed_everything(cfg.seed)

    # These arguments cannot be changed (reference main :157)
    cfg.env.screen_size = 64

    state = None
    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    n_envs = int(cfg.env.num_envs) * world_size
    # vector backend picked by env.vectorization (envs/vector/factory.py)
    envs = make_vector_env(cfg, fabric, log_dir)
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC-AE agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if (
        len(set(cfg.cnn_keys.encoder).intersection(set(cfg.cnn_keys.decoder))) == 0
        and len(set(cfg.mlp_keys.encoder).intersection(set(cfg.mlp_keys.decoder))) == 0
    ):
        raise RuntimeError("The CNN keys or the MLP keys of the encoder and decoder must not be disjoint")
    if len(set(cfg.cnn_keys.decoder) - set(cfg.cnn_keys.encoder)) > 0:
        raise RuntimeError(
            "The CNN keys of the decoder must be contained in the encoder ones. "
            f"Those keys are decoded without being encoded: {list(set(cfg.cnn_keys.decoder))}"
        )
    if len(set(cfg.mlp_keys.decoder) - set(cfg.mlp_keys.encoder)) > 0:
        raise RuntimeError(
            "The MLP keys of the decoder must be contained in the encoder ones. "
            f"Those keys are decoded without being encoded: {list(set(cfg.mlp_keys.decoder))}"
        )
    if cfg.metric.log_level > 0:
        fabric.print("Encoder CNN keys:", cfg.cnn_keys.encoder)
        fabric.print("Encoder MLP keys:", cfg.mlp_keys.encoder)
        fabric.print("Decoder CNN keys:", cfg.cnn_keys.decoder)
        fabric.print("Decoder MLP keys:", cfg.mlp_keys.decoder)
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    act_dim = int(np.prod(action_space.shape))
    action_scale, action_bias = action_bounds(action_space)
    target_entropy = -float(act_dim)

    root_key, build_key = jax.random.split(root_key)
    encoder, decoder, qf, actor_trunk, params = build_agent(
        cfg, act_dim, observation_space, build_key
    )

    txs = {
        "qf": instantiate(cfg.algo.critic.optimizer),
        "actor": instantiate(cfg.algo.actor.optimizer),
        "alpha": instantiate(cfg.algo.alpha.optimizer),
        "encoder": instantiate(cfg.algo.encoder.optimizer),
        "decoder": instantiate(cfg.algo.decoder.optimizer),
    }
    opt_states = {
        "qf": txs["qf"].init({"encoder": params["encoder"], "qfs": params["qfs"]}),
        "actor": txs["actor"].init(params["actor"]),
        "alpha": txs["alpha"].init(params["log_alpha"]),
        "encoder": txs["encoder"].init(params["encoder"]),
        "decoder": txs["decoder"].init(params["decoder"]),
    }

    if cfg.checkpoint.resume_from:
        template = {
            "agent": params,
            "opt_states": opt_states,
            "update": 0,
            "batch_size": 0,
            "last_log": 0,
            "last_checkpoint": 0,
        }
        state = fabric.load(cfg.checkpoint.resume_from, template)
        params = state["agent"]
        opt_states = state["opt_states"]
        cfg.per_rank_batch_size = int(np.asarray(state["batch_size"])) // world_size
    agent_state = jax.device_put(params, fabric.replicated)
    opt_states = jax.device_put(opt_states, fabric.replicated)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    rb = make_replay_buffer(
        cfg,
        fabric,
        log_dir,
        n_envs=n_envs,
        obs_keys=tuple(obs_keys),
        dry_run_size=1,
    )
    if state is not None and cfg.buffer.get("checkpoint", False) and "rb" in state:
        rb.load_state_dict(state["rb"])

    scale_j, bias_j = jnp.asarray(action_scale), jnp.asarray(action_bias)

    def _acting_subtree(p):
        return {"encoder": p["encoder"], "actor": p["actor"]}

    actor_mirror = HostParamMirror.from_cfg(_acting_subtree(agent_state), fabric, cfg)
    play_params = actor_mirror(_acting_subtree(agent_state))

    train_fn = build_train_fn(
        encoder, decoder, qf, actor_trunk, txs, cfg, fabric,
        action_scale, action_bias, target_entropy,
    )
    batch_sharding = fabric.sharding(None, fabric.data_axis)
    # TPU-first replay staging (data/staging.py): device-ring gathers when
    # buffer.device_ring=True, double-buffered host prefetch otherwise
    staging = make_replay_staging(
        cfg, fabric, rb, batch_sharding=batch_sharding, seed=cfg.seed
    )
    rb = staging.rb

    last_train = 0
    train_step = 0
    start_step = int(np.asarray(state["update"])) // world_size if state is not None else 1
    policy_step = int(np.asarray(state["update"])) * cfg.env.num_envs if state is not None else 0
    last_log = int(np.asarray(state["last_log"])) if state is not None else 0
    last_checkpoint = int(np.asarray(state["last_checkpoint"])) if state is not None else 0
    policy_steps_per_update = int(n_envs)
    num_updates = int(cfg.total_steps // policy_steps_per_update) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_update if not cfg.dry_run else 0
    if cfg.checkpoint.resume_from and not cfg.buffer.get("checkpoint", False):
        learning_starts += start_step

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_update != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_update value ({policy_steps_per_update})."
        )
    warn_checkpoint_rounding(cfg, policy_steps_per_update)

    o = envs.reset(seed=cfg.seed)[0]
    obs = prepare_obs(o, cnn_keys, mlp_keys, n_envs)
    root_key, play_key = jax.random.split(root_key)
    play_key = actor_mirror.put_key(play_key)

    per_rank_gradient_steps = int(cfg.algo.per_rank_gradient_steps)
    ema_every = int(cfg.algo.critic.target_network_frequency) // policy_steps_per_update + 1
    actor_every = int(cfg.algo.actor.network_frequency) // policy_steps_per_update + 1
    decoder_every = int(cfg.algo.decoder.update_freq) // policy_steps_per_update + 1

    # burst acting (envs/rollout, howto/rollout_engine.md): K env steps per
    # device dispatch; 1 (the default) reproduces the per-step path exactly
    act_burst = max(int(cfg.env.get("act_burst", 1) or 1), 1)

    # The acting loop body as one host function — env step, SAME_STEP
    # final_obs fixup, episode logging, buffer add: the old per-step block
    # verbatim. The BurstActor scans it K times per dispatch through an
    # ordered io_callback; the random prefill calls it directly.
    state_box = {"obs": obs, "policy_step": policy_step}

    def _host_env_step(actions):
        actions = np.asarray(actions)
        state_box["policy_step"] += n_envs
        with span("Time/env_interaction_time", SumMetric(sync_on_compute=False), phase="env"):
            next_o, rewards, terminated, truncated, infos = envs.step(
                actions.reshape(envs.action_space.shape)
            )
        dones = np.logical_or(terminated, truncated)

        if cfg.metric.log_level > 0 and "final_info" in infos:
            fi = infos["final_info"]
            if isinstance(fi, dict) and "episode" in fi:
                mask = np.asarray(fi.get("_episode", []), dtype=bool)
                for i in np.nonzero(mask)[0]:
                    ep_rew = float(fi["episode"]["r"][i])
                    ep_len = float(fi["episode"]["l"][i])
                    if aggregator and not aggregator.disabled:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                        aggregator.update("Game/ep_len_avg", ep_len)
                    fabric.print(
                        f"Rank-0: policy_step={state_box['policy_step']}, reward_env_{i}={ep_rew}"
                    )

        next_obs_np = {k: np.asarray(next_o[k]) for k in next_o}
        real_next_obs = {k: v.copy() for k, v in next_obs_np.items()}
        if "final_obs" in infos:
            for idx, final_obs in enumerate(infos["final_obs"]):
                if final_obs is not None:
                    for k in real_next_obs:
                        if k in final_obs:
                            real_next_obs[k][idx] = np.asarray(final_obs[k])

        next_obs = prepare_obs(next_obs_np, cnn_keys, mlp_keys, n_envs)
        real_next = prepare_obs(real_next_obs, cnn_keys, mlp_keys, n_envs)

        step_data = {k: state_box["obs"][k][None] for k in obs_keys}
        step_data["actions"] = np.asarray(actions, np.float32).reshape(1, n_envs, -1)
        step_data["rewards"] = np.asarray(rewards, np.float32).reshape(1, n_envs, 1)
        step_data["dones"] = np.asarray(dones, np.float32).reshape(1, n_envs, 1)
        if not cfg.buffer.sample_next_obs:
            for k in obs_keys:
                step_data[f"next_{k}"] = real_next[k][None]
        rb.add(step_data)
        state_box["obs"] = next_obs
        return next_obs

    def _act_fn(agent_params, a_obs, key):
        # key advances inside the jitted burst (same discipline as the old
        # per-step policy_fn, so K=1 is bitwise the per-step path); the
        # uint8→[0,1] normalize moved inside the traced program — same math
        key, sub = jax.random.split(key)
        norm_obs = normalize_obs_jnp(a_obs, cnn_keys)
        feat = encoder.apply({"params": agent_params["encoder"]}, norm_obs)
        mean, std = actor_trunk.apply({"params": agent_params["actor"]}, feat)
        actions, _ = squash_sample(mean, std, sub, scale_j, bias_j)
        return (actions,), key

    burst_actor = BurstActor(_act_fn, _host_env_step, obs)

    update = start_step
    while update <= num_updates:
        if update <= learning_starts:
            n_act = 1
            _host_env_step(envs.action_space.sample())
        else:
            n_act = max(min(act_burst, num_updates - update + 1), 1)
            with span("Time/rollout_time", SumMetric(sync_on_compute=False), phase="rollout"):
                _, play_key = burst_actor.rollout(
                    play_params, state_box["obs"], play_key, n_act
                )
        policy_step = state_box["policy_step"]
        first = update
        update += n_act
        last = update - 1

        # one train round per update index the burst covered (K=1 reduces to
        # the reference per-update cadence; the ema/actor/decoder gates use
        # the exact per-update index, so the cadences stay bitwise for any K)
        for u in range(first, last + 1):
            if u < learning_starts:
                continue
            training_steps = learning_starts if u == learning_starts else 1
            g_total = training_steps * per_rank_gradient_steps
            # [G, B*world, ...] device arrays: ring-gathered from HBM, or
            # host-sampled + device_put overlapped with the previous burst
            # (native dtypes either way: uint8 pixels are 4x cheaper over
            # the host->HBM link; the train step normalizes on device)
            batch = staging.sample_device(
                world_size * cfg.per_rank_batch_size,
                n_samples=g_total,
                sample_next_obs=cfg.buffer.sample_next_obs,
            )

            with span("Time/train_time", SumMetric(sync_on_compute=cfg.metric.sync_on_compute), phase="train"):
                root_key, train_key = jax.random.split(root_key)
                gates = {
                    "do_ema": jnp.bool_(u % ema_every == 0),
                    "do_actor": jnp.bool_(u % actor_every == 0),
                    "do_decoder": jnp.bool_(u % decoder_every == 0),
                }
                outs = train_fn(agent_state, opt_states, batch, train_key, gates)
                agent_state, opt_states, losses = outs[0], outs[1], outs[2]
                observe_probes(outs[3] if len(outs) > 3 else None, step=policy_step)
                losses = fetch_losses_if_observed(losses, aggregator)
            play_params = actor_mirror(_acting_subtree(agent_state))
            train_step += world_size

            if aggregator and not aggregator.disabled:
                aggregator.update("Loss/value_loss", losses[0])
                aggregator.update("Loss/policy_loss", losses[1])
                aggregator.update("Loss/alpha_loss", losses[2])
                aggregator.update("Loss/reconstruction_loss", losses[3])

        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or last == num_updates
        ):
            if aggregator and not aggregator.disabled:
                metrics_dict = aggregator.compute()
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                aggregator.reset()
            log_sps_metrics(
                logger,
                policy_step=policy_step,
                last_log=last_log,
                train_step=train_step,
                last_train=last_train,
                world_size=world_size,
                action_repeat=cfg.env.action_repeat,
            )
            profile_tick(policy_step=policy_step, world_size=world_size)
            last_log = policy_step
            last_train = train_step

        if should_checkpoint(cfg, policy_step, last_checkpoint, last, num_updates):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": jax.device_get(agent_state),
                "opt_states": jax.device_get(opt_states),
                "update": last * world_size,
                "batch_size": cfg.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{fabric.global_rank}")
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.get("checkpoint", False) else None,
            )
            if preemption_requested():
                # SIGTERM/SIGINT: the final checkpoint is saved (the CLI
                # drains the in-flight write) — leave the train loop cleanly
                break

    staging.close()
    envs.close()
    if fabric.is_global_zero and cfg.algo.get("run_test", True) and not preemption_requested():
        test(
            encoder, actor_trunk, jax.device_get(agent_state), scale_j, bias_j,
            fabric, cfg, log_dir,
        )
