"""SAC-AE agent — pixel SAC with a convolutional autoencoder.

Behavioral contract from the reference ``sheeprl/algos/sac_ae/agent.py``
(CNNEncoder :19-77, MLPEncoder :79-107, MLPDecoder :109-138, CNNDecoder
:140-189, SACAEQFunction :191-211, SACAECritic :213-225,
SACAEContinuousActor :227-320, SACAEAgent :323-520):

- the conv encoder is 4×(k=3 convs, stride [2,1,1,1]) + a
  ``Dense→LayerNorm→tanh`` projection; ``detach_encoder_features`` stops
  gradients *between* the convs and the projection (reference :70-77);
- the critic owns the encoder (so the Q loss trains it); the actor reuses
  the critic's encoder but only its own trunk/head parameters receive
  gradients;
- the decoder inverts the convs and regresses 5-bit-quantized pixels with an
  L2 penalty on the latent (reference sac_ae.py:115-131);
- separate EMA taus for the target Q heads (``algo.tau``) and the target
  encoder (``algo.encoder.tau``);
- delta-orthogonal init for convs, orthogonal for linears (reference
  utils.py weight_init :74-93).

TPU-native: the twin-Q ensemble is stacked params under ``jax.vmap`` (one
batched matmul), targets are plain pytrees EMA'd inside the jitted step.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.models.norm import FastLayerNorm

from sheeprl_tpu.models.models import MLP, resolve_activation

LOG_STD_MAX = 2.0
LOG_STD_MIN = -10.0

sg = jax.lax.stop_gradient


def conv_output_hw(screen: int) -> int:
    """Spatial size after the k=3 stride-[2,1,1,1] encoder stack."""
    h = (screen - 3) // 2 + 1
    for _ in range(3):
        h = h - 2
    return h


class SACAECNNEncoder(nn.Module):
    """Conv stack + Dense/LayerNorm/tanh projection (reference :19-77).
    Input ``[..., C, H, W]``; ``detach_conv`` stops gradients before the
    projection."""

    keys: Sequence[str]
    features_dim: int
    channels_multiplier: int = 1

    @nn.compact
    def __call__(self, obs: Dict[str, jnp.ndarray], detach_conv: bool = False) -> jnp.ndarray:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-3)
        lead = x.shape[:-3]
        x = jnp.reshape(x, (-1,) + x.shape[-3:])
        x = jnp.moveaxis(x, -3, -1)  # NHWC
        for i, stride in enumerate((2, 1, 1, 1)):
            x = nn.Conv(32 * self.channels_multiplier, (3, 3), strides=(stride, stride), padding="VALID")(x)
            x = nn.relu(x)
        x = jnp.reshape(x, (x.shape[0], -1))
        if detach_conv:
            x = sg(x)
        x = nn.Dense(self.features_dim)(x)
        x = FastLayerNorm(name="LayerNorm_0")(x)
        x = jnp.tanh(x)
        return jnp.reshape(x, lead + (self.features_dim,))


class SACAEMLPEncoder(nn.Module):
    """Dense stack over the vector keys (reference :79-107)."""

    keys: Sequence[str]
    dense_units: int = 64
    mlp_layers: int = 2
    activation: Any = "relu"
    layer_norm: bool = False

    @nn.compact
    def __call__(self, obs: Dict[str, jnp.ndarray], detach_conv: bool = False) -> jnp.ndarray:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-1)
        x = MLP(
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
        )(x)
        if detach_conv:
            x = sg(x)
        return x


class SACAEEncoder(nn.Module):
    """Concat of cnn/mlp sub-encoders (reference MultiEncoder wiring,
    sac_ae.py :216-243)."""

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    features_dim: int
    channels_multiplier: int = 1
    dense_units: int = 64
    mlp_layers: int = 2
    dense_act: Any = "relu"
    layer_norm: bool = False

    @property
    def output_dim(self) -> int:
        dim = self.features_dim if self.cnn_keys else 0
        dim += self.dense_units if self.mlp_keys else 0
        return dim

    @nn.compact
    def __call__(self, obs: Dict[str, jnp.ndarray], detach_conv: bool = False) -> jnp.ndarray:
        feats = []
        if self.cnn_keys:
            feats.append(
                SACAECNNEncoder(
                    keys=self.cnn_keys,
                    features_dim=self.features_dim,
                    channels_multiplier=self.channels_multiplier,
                    name="cnn_encoder",
                )(obs, detach_conv)
            )
        if self.mlp_keys:
            feats.append(
                SACAEMLPEncoder(
                    keys=self.mlp_keys,
                    dense_units=self.dense_units,
                    mlp_layers=self.mlp_layers,
                    activation=self.dense_act,
                    layer_norm=self.layer_norm,
                    name="mlp_encoder",
                )(obs, detach_conv)
            )
        return jnp.concatenate(feats, axis=-1) if len(feats) > 1 else feats[0]


class SACAECNNDecoder(nn.Module):
    """Inverse of the conv encoder (reference :140-189): Dense back to the
    conv map, 3×(k=3, s=1) transposed convs, then a final k=3/s=2 transposed
    conv with output-padding 1 back to ``screen×screen``."""

    output_channels: Sequence[int]
    conv_hw: int
    channels_multiplier: int = 1

    @nn.compact
    def __call__(self, latent: jnp.ndarray) -> jnp.ndarray:
        c = 32 * self.channels_multiplier
        lead = latent.shape[:-1]
        x = nn.Dense(c * self.conv_hw * self.conv_hw)(latent)
        x = jnp.reshape(x, (-1, self.conv_hw, self.conv_hw, c))
        for _ in range(3):
            x = nn.ConvTranspose(c, (3, 3), strides=(1, 1), padding="VALID", transpose_kernel=True)(x)
            x = nn.relu(x)
        x = nn.ConvTranspose(
            sum(self.output_channels), (3, 3), strides=(2, 2), padding="VALID", transpose_kernel=True
        )(x)
        # torch output_padding=1: one extra row/col at bottom/right
        x = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))
        x = jnp.moveaxis(x, -1, -3)  # back to CHW
        return jnp.reshape(x, lead + x.shape[1:])


class SACAEMLPDecoder(nn.Module):
    """Dense trunk + per-key heads (reference :109-138)."""

    keys: Sequence[str]
    output_dims: Sequence[int]
    dense_units: int = 64
    mlp_layers: int = 2
    activation: Any = "relu"

    @nn.compact
    def __call__(self, latent: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        x = MLP(
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
        )(latent)
        return {
            k: nn.Dense(dim, name=f"head_{k}")(x)
            for k, dim in zip(self.keys, self.output_dims)
        }


class SACAEDecoder(nn.Module):
    """Per-key reconstructions from the encoder latent."""

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_channels: Sequence[int]
    mlp_dims: Sequence[int]
    conv_hw: int
    channels_multiplier: int = 1
    dense_units: int = 64
    mlp_layers: int = 2
    dense_act: Any = "relu"

    @nn.compact
    def __call__(self, latent: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        out: Dict[str, jnp.ndarray] = {}
        if self.cnn_keys:
            rec = SACAECNNDecoder(
                output_channels=self.cnn_channels,
                conv_hw=self.conv_hw,
                channels_multiplier=self.channels_multiplier,
                name="cnn_decoder",
            )(latent)
            if len(self.cnn_keys) > 1:
                parts = jnp.split(rec, np.cumsum(np.asarray(self.cnn_channels))[:-1], axis=-3)
            else:
                parts = [rec]
            out.update({k: v for k, v in zip(self.cnn_keys, parts)})
        if self.mlp_keys:
            out.update(
                SACAEMLPDecoder(
                    keys=self.mlp_keys,
                    output_dims=self.mlp_dims,
                    dense_units=self.dense_units,
                    mlp_layers=self.mlp_layers,
                    activation=self.dense_act,
                    name="mlp_decoder",
                )(latent)
            )
        return out


class SACAEQFunction(nn.Module):
    """Q(features, action) MLP (reference :191-211); applied under vmap over
    the stacked twin-critic axis."""

    hidden_size: int = 1024

    @nn.compact
    def __call__(self, features: jnp.ndarray, action: jnp.ndarray) -> jnp.ndarray:
        x = jnp.concatenate([features, action], -1)
        x = MLP(hidden_sizes=(self.hidden_size, self.hidden_size), activation="relu")(x)
        return nn.Dense(1)(x)


class SACAEActorTrunk(nn.Module):
    """Actor trunk + (mean, log_std) heads over encoder features
    (reference SACAEContinuousActor :227-320): the log-std is tanh-scaled
    into [LOG_STD_MIN, LOG_STD_MAX]."""

    action_dim: int
    hidden_size: int = 1024

    @nn.compact
    def __call__(self, features: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        x = MLP(hidden_sizes=(self.hidden_size, self.hidden_size), activation="relu")(features)
        mean = nn.Dense(self.action_dim, name="fc_mean")(x)
        log_std = nn.Dense(self.action_dim, name="fc_logstd")(x)
        log_std = jnp.tanh(log_std)
        log_std = LOG_STD_MIN + 0.5 * (LOG_STD_MAX - LOG_STD_MIN) * (log_std + 1.0)
        return mean, jnp.exp(log_std)


# ---------------------------------------------------------------------------
# ensemble helpers (same stacked-params pattern as sac/agent.py)
# ---------------------------------------------------------------------------


def init_qf_ensemble(qf: SACAEQFunction, n: int, feat_dim: int, act_dim: int, key: jax.Array):
    keys = jax.random.split(key, n)
    trees = [
        qf.init(k, jnp.zeros((1, feat_dim)), jnp.zeros((1, act_dim)))["params"] for k in keys
    ]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, 0), *trees)


def ensemble_q(qf: SACAEQFunction, stacked, features, action) -> jnp.ndarray:
    out = jax.vmap(lambda p: qf.apply({"params": p}, features, action))(stacked)
    return jnp.moveaxis(out[..., 0], 0, -1)  # [..., n_critics]


# ---------------------------------------------------------------------------
# init (reference utils.py weight_init :74-93)
# ---------------------------------------------------------------------------


def _orthogonal(key: jax.Array, shape, dtype=jnp.float32) -> jnp.ndarray:
    return nn.initializers.orthogonal()(key, shape, dtype)


def sac_ae_weight_init(params: Dict[str, Any], key: jax.Array) -> Dict[str, Any]:
    """Orthogonal init for dense kernels; delta-orthogonal for convs (the
    center spatial tap is orthogonal, the rest zero); biases zero."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    keys = jax.random.split(key, max(len(flat), 1))
    leaves = []
    for i, (path, leaf) in enumerate(flat):
        name = "/".join(getattr(p, "key", str(p)) for p in path)
        if name.endswith("kernel") and leaf.ndim == 2:
            leaves.append(_orthogonal(keys[i], leaf.shape, leaf.dtype))
        elif name.endswith("kernel") and leaf.ndim == 4:
            kh, kw = leaf.shape[:2]
            center = jnp.zeros_like(leaf)
            tap = _orthogonal(keys[i], leaf.shape[2:], leaf.dtype)
            leaves.append(center.at[kh // 2, kw // 2].set(tap))
        elif name.endswith("bias"):
            leaves.append(jnp.zeros_like(leaf))
        else:
            leaves.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------


def build_agent(cfg, act_dim: int, observation_space, key: jax.Array):
    """Construct module defs + initialized params.

    Returns ``(encoder, decoder, qf, actor_trunk, params)`` with ``params =
    {encoder, target_encoder, qfs, target_qfs, actor, decoder, log_alpha}``.
    """
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    screen = int(cfg.env.screen_size)
    cnn_channels = [int(np.prod(observation_space[k].shape[:-2])) for k in cnn_keys]
    mlp_dims = [int(np.prod(observation_space[k].shape)) for k in mlp_keys]

    encoder = SACAEEncoder(
        cnn_keys=cnn_keys,
        mlp_keys=mlp_keys,
        features_dim=int(cfg.algo.encoder.features_dim),
        channels_multiplier=int(cfg.algo.encoder.cnn_channels_multiplier),
        dense_units=int(cfg.algo.encoder.dense_units),
        mlp_layers=int(cfg.algo.encoder.mlp_layers),
        dense_act=cfg.algo.encoder.dense_act,
        layer_norm=bool(cfg.algo.encoder.layer_norm),
    )
    decoder = SACAEDecoder(
        cnn_keys=list(cfg.cnn_keys.decoder),
        mlp_keys=list(cfg.mlp_keys.decoder),
        cnn_channels=[int(np.prod(observation_space[k].shape[:-2])) for k in cfg.cnn_keys.decoder],
        mlp_dims=[int(np.prod(observation_space[k].shape)) for k in cfg.mlp_keys.decoder],
        conv_hw=conv_output_hw(screen),
        channels_multiplier=int(cfg.algo.decoder.cnn_channels_multiplier),
        dense_units=int(cfg.algo.decoder.dense_units),
        mlp_layers=int(cfg.algo.decoder.mlp_layers),
        dense_act=cfg.algo.decoder.dense_act,
    )
    qf = SACAEQFunction(hidden_size=int(cfg.algo.critic.hidden_size))
    actor_trunk = SACAEActorTrunk(
        action_dim=act_dim, hidden_size=int(cfg.algo.actor.hidden_size)
    )

    k_enc, k_qf, k_actor, k_dec, k_i1, k_i2, k_i3, k_i4 = jax.random.split(key, 8)
    dummy_obs = {}
    for k, ch in zip(cnn_keys, cnn_channels):
        dummy_obs[k] = jnp.zeros((1, ch, screen, screen), jnp.float32)
    for k, dim in zip(mlp_keys, mlp_dims):
        dummy_obs[k] = jnp.zeros((1, dim), jnp.float32)

    enc_params = encoder.init(k_enc, dummy_obs)["params"]
    feat_dim = encoder.output_dim
    qfs = init_qf_ensemble(qf, int(cfg.algo.critic.n), feat_dim, act_dim, k_qf)
    actor_params = actor_trunk.init(k_actor, jnp.zeros((1, feat_dim)))["params"]
    dec_params = decoder.init(k_dec, jnp.zeros((1, feat_dim)))["params"]

    enc_params = sac_ae_weight_init(enc_params, k_i1)
    qfs = sac_ae_weight_init(qfs, k_i2)
    actor_params = sac_ae_weight_init(actor_params, k_i3)
    dec_params = sac_ae_weight_init(dec_params, k_i4)

    params = {
        "encoder": enc_params,
        "target_encoder": jax.tree_util.tree_map(jnp.copy, enc_params),
        "qfs": qfs,
        "target_qfs": jax.tree_util.tree_map(jnp.copy, qfs),
        "actor": actor_params,
        "decoder": dec_params,
        "log_alpha": jnp.log(jnp.float32(cfg.algo.alpha.alpha)),
    }
    return encoder, decoder, qf, actor_trunk, params


def preprocess_obs(obs: jnp.ndarray, bits: int = 8, key=None) -> jnp.ndarray:
    """Bit-quantized pixel target (reference utils.py:63-71,
    https://arxiv.org/abs/1807.03039): floor to ``bits`` bits, rescale to
    [−0.5, 0.5] with uniform dequantization noise when a key is given."""
    bins = 2**bits
    if bits < 8:
        obs = jnp.floor(obs / 2 ** (8 - bits))
    obs = obs / bins
    if key is not None:
        obs = obs + jax.random.uniform(key, obs.shape, obs.dtype) / bins
    return obs - 0.5
