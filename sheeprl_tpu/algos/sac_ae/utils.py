"""SAC-AE helpers (reference ``sheeprl/algos/sac_ae/utils.py``)."""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.utils import (  # noqa: F401  (same dict-obs pipeline)
    normalize_obs_jnp,
    prepare_obs,
)
from sheeprl_tpu.envs.vector import make_eval_env

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
    "Loss/reconstruction_loss",
}


def test(encoder, actor_trunk, params, action_scale, action_bias, fabric, cfg, log_dir: str) -> None:
    """Greedy single-env evaluation episode (reference utils.py:23-50)."""
    env = make_eval_env(cfg, log_dir)
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)

    @jax.jit
    def act(p, obs):
        feat = encoder.apply({"params": p["encoder"]}, obs)
        mean, _ = actor_trunk.apply({"params": p["actor"]}, feat)
        return jnp.tanh(mean) * action_scale + action_bias

    done = False
    cumulative_rew = 0.0
    o = env.reset(seed=cfg.seed)[0]
    while not done:
        obs = prepare_obs(o, cnn_keys, mlp_keys, 1)
        norm = normalize_obs_jnp(obs, cnn_keys)
        action = np.asarray(act(params, norm))
        o, reward, terminated, truncated, _ = env.step(action.reshape(env.action_space.shape))
        done = bool(terminated or truncated)
        cumulative_rew += float(reward)
        if cfg.dry_run:
            done = True
    fabric.print("Test - Reward:", cumulative_rew)
    if cfg.metric.log_level > 0 and getattr(fabric, "logger", None) is not None:
        fabric.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()
