"""Atomic checkpoint-directory writer.

Everything lands in ``<final>.tmp/`` first: npz state shard, per-env replay
buffer shards, then ``manifest.json`` last — each file flushed and fsynced —
and the directory is renamed to its final name only after a directory fsync.
``rename(2)`` is atomic on POSIX, so a reader (or a resumed run) either sees
a complete, manifest-valid checkpoint or a ``.tmp`` partial it must skip; a
writer killed at any instruction can never half-produce a final directory.

Replay-buffer states shard per environment instead of one monolithic pickle:

- plain :class:`~sheeprl_tpu.data.buffers.ReplayBuffer`-style states
  (``{"buffer": {k: [size, n_envs, ...]}, "pos", "full"}``) slice along the
  env axis into ``rb_env<i>.npz``;
- :class:`~sheeprl_tpu.data.buffers.EnvIndependentReplayBuffer` states
  (``{"buffers": [...]}``, and the callback's ``{"__list__": [...]}`` wrap)
  write one shard per sub-buffer;
- anything else (EpisodeBuffer's ragged episode lists) falls back to one
  generic treedef shard, still npz.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Dict, Optional, Tuple

import numpy as np

from sheeprl_tpu.ckpt.manifest import (
    SCHEMA_VERSION,
    encode_array,
    flatten_tree,
    write_manifest,
)

__all__ = ["write_checkpoint", "TMP_SUFFIX", "OLD_SUFFIX"]

TMP_SUFFIX = ".tmp"
#: a same-step overwrite parks the previous final dir here for the instant
#: between the two renames, so a kill at any point leaves either the old or
#: the new checkpoint fully intact (never a window with neither)
OLD_SUFFIX = ".old"


def _fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_npz(path: str, arrays: Dict[str, np.ndarray], fsync: bool = True) -> int:
    with open(path, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    return os.path.getsize(path)


def _env_sliced_plan(rb_state: Any) -> Optional[int]:
    """n_envs when ``rb_state`` is a ReplayBuffer-style state whose buffer
    arrays all share an env axis (dim 1); None → not env-sliceable."""
    if not isinstance(rb_state, dict) or not isinstance(rb_state.get("buffer"), dict):
        return None
    if set(rb_state) - {"buffer", "pos", "full"}:
        return None
    n_envs = None
    for v in rb_state["buffer"].values():
        arr = np.asarray(v)
        if arr.ndim < 2:
            return None
        if n_envs is None:
            n_envs = arr.shape[1]
        elif arr.shape[1] != n_envs:
            return None
    return n_envs if n_envs else None


def _flatten_rb(
    rb_state: Any, tmp_dir: str, fsync: bool
) -> Tuple[Dict[str, Any], Dict[str, int]]:
    """Write the replay-buffer shards into ``tmp_dir``; returns the manifest
    ``rb`` section and the per-file byte sizes."""
    files: Dict[str, int] = {}

    n_envs = _env_sliced_plan(rb_state)
    if n_envs is not None:
        shards = []
        for i in range(n_envs):
            arrays: Dict[str, np.ndarray] = {}
            leaf_meta: Dict[str, Any] = {}
            for j, (k, v) in enumerate(sorted(rb_state["buffer"].items())):
                stored, meta = encode_array(np.ascontiguousarray(np.asarray(v)[:, i]))
                key = f"b{j}"
                arrays[key] = stored
                meta["key"] = key
                leaf_meta[k] = meta
            fname = f"rb_env{i}.npz"
            files[fname] = _write_npz(os.path.join(tmp_dir, fname), arrays, fsync)
            shards.append({"file": fname, "arrays": leaf_meta})
        return (
            {
                "kind": "env_sliced",
                "n_envs": n_envs,
                "pos": int(np.asarray(rb_state.get("pos", 0))),
                "full": bool(np.asarray(rb_state.get("full", False))),
                "shards": shards,
            },
            files,
        )

    container = None
    if isinstance(rb_state, dict):
        for key in ("buffers", "__list__"):
            if key in rb_state and isinstance(rb_state[key], list) and len(rb_state) == 1:
                container = key
    if container is not None:
        shards = []
        for i, sub in enumerate(rb_state[container]):
            arrays = {}
            treedef = flatten_tree(sub, arrays)
            fname = f"rb_env{i}.npz"
            files[fname] = _write_npz(os.path.join(tmp_dir, fname), arrays, fsync)
            shards.append({"file": fname, "tree": treedef})
        return {"kind": "per_buffer", "container": container, "shards": shards}, files

    arrays = {}
    treedef = flatten_tree(rb_state, arrays)
    files["rb.npz"] = _write_npz(os.path.join(tmp_dir, "rb.npz"), arrays, fsync)
    return {"kind": "tree", "file": "rb.npz", "tree": treedef}, files


def write_checkpoint(
    final_dir: str,
    state: Optional[Dict[str, Any]],
    rb_state: Any = None,
    *,
    step: Optional[int] = None,
    rank: int = 0,
    world_size: int = 1,
    algo: Optional[str] = None,
    config_hash: Optional[str] = None,
    fsync: bool = True,
    sharding: Optional[Dict[str, Any]] = None,
) -> int:
    """Write one checkpoint directory atomically; returns bytes written.

    ``state=None`` (non-zero ranks of a replicated model) writes buffer
    shards + manifest only — resume reads the model from the rank-0 sibling.

    ``sharding`` records the :meth:`ShardingPlan.describe` layout the state
    was trained under (mesh axes + per-leaf specs). The state arrays
    themselves are always written *gathered* (full shapes), so restore needs
    no shard reassembly and is free to re-spec onto a different
    ``model_axis`` — the manifest section pins down provenance and lets
    tooling verify what layout produced the numbers.
    """
    final_dir = os.path.abspath(final_dir)
    tmp_dir = final_dir + TMP_SUFFIX
    if os.path.isdir(tmp_dir):
        shutil.rmtree(tmp_dir, ignore_errors=True)
    os.makedirs(tmp_dir)

    files: Dict[str, int] = {}
    manifest: Dict[str, Any] = {
        "schema_version": SCHEMA_VERSION,
        "step": step,
        "rank": int(rank),
        "world_size": int(world_size),
        "algo": algo,
        "config_hash": config_hash,
        "state": None,
        "rb": None,
        "sharding": sharding,
    }

    if state is not None:
        arrays: Dict[str, np.ndarray] = {}
        manifest["state"] = {"file": "state.npz", "tree": flatten_tree(state, arrays)}
        files["state.npz"] = _write_npz(os.path.join(tmp_dir, "state.npz"), arrays, fsync)

    if rb_state is not None:
        manifest["rb"], rb_files = _flatten_rb(rb_state, tmp_dir, fsync)
        files.update(rb_files)

    manifest["files"] = files
    write_manifest(tmp_dir, manifest, fsync=fsync)
    if fsync:
        _fsync_path(tmp_dir)

    old_dir = final_dir + OLD_SUFFIX
    if os.path.isdir(final_dir):
        # same-step overwrite (a resumed run re-writing its save_last step):
        # park the valid old dir aside instead of deleting it, so a kill
        # between here and the rename below cannot lose the only checkpoint
        # for this step — resolve_latest ignores the .old name
        if os.path.isdir(old_dir):
            shutil.rmtree(old_dir, ignore_errors=True)
        os.replace(final_dir, old_dir)
    os.replace(tmp_dir, final_dir)
    if fsync:
        _fsync_path(os.path.dirname(final_dir) or ".")
    shutil.rmtree(old_dir, ignore_errors=True)
    return sum(files.values())
