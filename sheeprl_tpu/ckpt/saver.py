"""Async checkpoint persistence: double-buffered background writes.

The Check-N-Run / t5x split: the step path pays only for the device→host
snapshot; serialization and disk I/O run on a dedicated writer thread. The
buffering discipline is *double* buffering — at most one save in flight, and
a new request first waits for the previous one to land (bounding host memory
at two snapshots and guaranteeing saves hit disk in step order) instead of
stacking a queue the filesystem can't drain.

Failure policy: every write runs under a bounded-retry/backoff wrapper
(transient filesystem hiccups — NFS timeouts, ENOSPC races with GC — get
``retries`` attempts). If a background write still fails, the saver marks
itself degraded and subsequent saves run *synchronously on the caller's
thread*, so persistent storage trouble surfaces in the train loop as a
raised exception instead of checkpoints silently stopping.
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import Callable, Optional

from sheeprl_tpu.obs.counters import add_ckpt_write

__all__ = ["AsyncSaver"]


class AsyncSaver:
    def __init__(self, retries: int = 3, backoff_s: float = 0.5):
        self.retries = max(0, int(retries))
        self.backoff_s = float(backoff_s)
        self._submit_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._degraded = False
        self.last_error: Optional[BaseException] = None

    # -- internals ----------------------------------------------------------

    def _attempt(self, write_fn: Callable[[], int], label: str) -> None:
        """Run ``write_fn`` under retry/backoff; accounts telemetry counters.
        Raises the final error after exhausting retries."""
        t0 = time.perf_counter()
        delay = self.backoff_s
        for attempt in range(self.retries + 1):
            try:
                nbytes = write_fn()
                add_ckpt_write((time.perf_counter() - t0) * 1000.0, nbytes or 0)
                return
            except OSError as exc:
                self.last_error = exc
                if attempt >= self.retries:
                    add_ckpt_write((time.perf_counter() - t0) * 1000.0, 0, failed=True)
                    raise
                warnings.warn(
                    f"checkpoint write {label} failed (attempt {attempt + 1}/"
                    f"{self.retries + 1}): {exc}; retrying in {delay:.1f}s"
                )
                time.sleep(delay)
                delay *= 2

    def _run_background(self, write_fn: Callable[[], int], label: str) -> None:
        try:
            self._attempt(write_fn, label)
        except BaseException as exc:  # noqa: BLE001 - must not kill the writer thread
            self._degraded = True
            self.last_error = exc
            warnings.warn(
                f"async checkpoint write {label} failed after "
                f"{self.retries + 1} attempts: {exc!r}; degrading to "
                "synchronous saves so further failures surface in the train loop"
            )

    # -- API ----------------------------------------------------------------

    @property
    def degraded(self) -> bool:
        return self._degraded

    def wait_for_inflight(self, timeout: Optional[float] = None) -> None:
        t = self._thread
        if t is not None:
            t.join(timeout)

    def submit(self, write_fn: Callable[[], int], label: str = "", sync: bool = False) -> None:
        """Persist one checkpoint. Async unless ``sync`` or degraded.

        Blocks only while a previous save is still in flight (double-buffer
        rule); the caller measures that wait as part of its blocked time.
        """
        with self._submit_lock:
            self.wait_for_inflight()
            self._thread = None
            if sync or self._degraded:
                self._attempt(write_fn, label)
                return
            try:
                thread = threading.Thread(
                    target=self._run_background,
                    args=(write_fn, label),
                    name="ckpt-writer",
                    daemon=True,
                )
                thread.start()
            except RuntimeError as exc:  # thread limit / interpreter teardown
                warnings.warn(f"cannot start checkpoint writer thread ({exc}); saving synchronously")
                self._attempt(write_fn, label)
                return
            self._thread = thread

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait for the in-flight save (if any). True when nothing is left."""
        self.wait_for_inflight(timeout)
        t = self._thread
        done = t is None or not t.is_alive()
        if done:
            self._thread = None
        return done
