"""Checkpoint manifest: the pytree↔npz codec and its integrity metadata.

A manifest-format checkpoint directory holds plain ``.npz`` shards plus one
``manifest.json`` describing everything in them:

- ``schema_version`` — layout version; readers refuse versions they do not
  understand instead of half-loading;
- ``step`` / ``rank`` / ``world_size`` / ``algo`` / ``config_hash`` — run
  identity (the hash is informational: resume already merges the persisted
  config, the manifest just records which one produced the arrays);
- ``files`` — per-file byte sizes (cheap liveness check for ``latest``
  resolution without opening the zips);
- ``state`` / ``rb`` — a JSON *treedef* mirroring the saved pytree, each
  leaf carrying the npz key, shape, dtype and crc32 of the stored bytes.

The treedef makes reconstruction unambiguous (no guessing whether digit
keys meant a list) and doubles as the per-array checksum table. NamedTuples
(optax states) are recorded with their field names and restored as plain
field dicts — ``Fabric.load``'s existing ``conform_pytree`` pass rebuilds
the concrete classes against the caller's live template, exactly as it does
for orbax restores.

npz stores only builtin numpy dtypes faithfully; anything else (bfloat16 &
friends from ml_dtypes) round-trips as a raw byte buffer with the true dtype
name recorded in the leaf — ``np.savez`` would silently degrade them to
void scalars otherwise.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Any, Dict, Tuple

import numpy as np

__all__ = [
    "SCHEMA_VERSION",
    "MANIFEST_NAME",
    "CheckpointCorruptedError",
    "array_crc32",
    "decode_array",
    "encode_array",
    "flatten_tree",
    "read_manifest",
    "unflatten_tree",
    "write_manifest",
]

SCHEMA_VERSION = 1
MANIFEST_NAME = "manifest.json"

#: dtype kinds npz round-trips faithfully (bool/int/uint/float/complex/str/bytes)
_NATIVE_KINDS = "?biufcSU"


class CheckpointCorruptedError(RuntimeError):
    """A checkpoint failed verification (checksum/shape/dtype/layout)."""


# -- array codec ------------------------------------------------------------


def array_crc32(arr: np.ndarray) -> int:
    """crc32 of the array's C-contiguous bytes (the stored representation)."""
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def encode_array(value: Any) -> Tuple[np.ndarray, Dict[str, Any]]:
    """``value`` → (npz-storable array, leaf metadata sans npz key).

    Native-dtype arrays store as-is; exotic dtypes (bfloat16, ...) store as a
    flat uint8 buffer with the true dtype/shape recorded for decode. The
    crc32 always covers the *stored* bytes so verification never has to know
    about dtypes.
    """
    arr = np.asarray(value)
    if arr.dtype.hasobject:
        raise TypeError(
            f"checkpoint state contains a non-array object leaf (dtype={arr.dtype}); "
            "only numeric/bool/string leaves are checkpointable"
        )
    meta: Dict[str, Any] = {"shape": list(arr.shape), "dtype": arr.dtype.name}
    if arr.dtype.kind not in _NATIVE_KINDS:
        arr = np.frombuffer(np.ascontiguousarray(arr).tobytes(), dtype=np.uint8)
        meta["stored_as"] = "raw_bytes"
    meta["crc32"] = array_crc32(arr)
    return arr, meta


def decode_array(stored: np.ndarray, meta: Dict[str, Any]) -> np.ndarray:
    if meta.get("stored_as") == "raw_bytes":
        dtype = np.dtype(meta["dtype"])  # ml_dtypes registers bfloat16 et al.
        return np.frombuffer(stored.tobytes(), dtype=dtype).reshape(meta["shape"])
    return stored


def _verify_leaf(stored: np.ndarray, meta: Dict[str, Any], path: str, where: str) -> None:
    if array_crc32(stored) != meta["crc32"]:
        raise CheckpointCorruptedError(
            f"checksum mismatch for array {path!r} in {where} — the checkpoint "
            "is corrupt (partial write or bit rot); refusing to resume from it"
        )


# -- pytree <-> (treedef, arrays) -------------------------------------------


def flatten_tree(tree: Any, arrays: Dict[str, np.ndarray], prefix: str = "a") -> Dict[str, Any]:
    """Flatten ``tree`` into ``arrays`` (npz key → storable array), returning
    the JSON treedef. Containers: dict / list / tuple / NamedTuple / None."""
    counter = [len(arrays)]

    def rec(node: Any, path: str) -> Dict[str, Any]:
        if node is None:
            return {"__type__": "none"}
        if isinstance(node, dict):
            return {
                "__type__": "dict",
                "items": [[k, rec(v, f"{path}/{k}")] for k, v in node.items()],
            }
        if isinstance(node, tuple) and hasattr(node, "_fields"):  # NamedTuple
            return {
                "__type__": "namedtuple",
                "name": type(node).__name__,
                "items": [
                    [f, rec(v, f"{path}/{f}")] for f, v in zip(node._fields, node)
                ],
            }
        if isinstance(node, (list, tuple)):
            return {
                "__type__": "list" if isinstance(node, list) else "tuple",
                "items": [rec(v, f"{path}/{i}") for i, v in enumerate(node)],
            }
        stored, meta = encode_array(node)
        key = f"{prefix}{counter[0]}"
        counter[0] += 1
        arrays[key] = stored
        leaf = {"__type__": "leaf", "key": key, "path": path}
        leaf.update(meta)
        return leaf

    return rec(tree, "")


def unflatten_tree(
    treedef: Dict[str, Any],
    arrays: Dict[str, np.ndarray],
    verify: bool = True,
    where: str = "checkpoint",
) -> Any:
    """Rebuild the pytree described by ``treedef`` from loaded npz ``arrays``.

    NamedTuples come back as field dicts (``conform_pytree`` rebuilds the
    classes against the live template); tuples come back as tuples. With
    ``verify`` every array is checksummed against the manifest.
    """

    def rec(node: Dict[str, Any]) -> Any:
        kind = node["__type__"]
        if kind == "none":
            return None
        if kind in ("dict", "namedtuple"):
            return {k: rec(v) for k, v in node["items"]}
        if kind in ("list", "tuple"):
            out = [rec(v) for v in node["items"]]
            return tuple(out) if kind == "tuple" else out
        if kind == "leaf":
            try:
                stored = arrays[node["key"]]
            except KeyError:
                raise CheckpointCorruptedError(
                    f"array {node.get('path') or node['key']!r} is missing from "
                    f"{where} — the checkpoint shards are incomplete"
                ) from None
            if verify:
                _verify_leaf(stored, node, node.get("path") or node["key"], where)
            return decode_array(stored, node)
        raise CheckpointCorruptedError(f"unknown treedef node type {kind!r} in {where}")

    return rec(treedef)


# -- manifest I/O -----------------------------------------------------------


def write_manifest(dirname: str, manifest: Dict[str, Any], fsync: bool = True) -> None:
    """Write ``manifest.json`` — the commit record of a checkpoint dir, so it
    is written last and fsynced before the directory is renamed final."""
    path = os.path.join(dirname, MANIFEST_NAME)
    with open(path, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.write("\n")
        f.flush()
        if fsync:
            os.fsync(f.fileno())


def read_manifest(dirname: str) -> Dict[str, Any]:
    path = os.path.join(dirname, MANIFEST_NAME)
    try:
        with open(path) as f:
            manifest = json.load(f)
    except FileNotFoundError:
        raise
    except (json.JSONDecodeError, OSError, UnicodeDecodeError) as exc:
        raise CheckpointCorruptedError(f"unreadable manifest at {path}: {exc}") from exc
    if not isinstance(manifest, dict) or "schema_version" not in manifest:
        raise CheckpointCorruptedError(f"malformed manifest at {path}")
    version = manifest["schema_version"]
    if not isinstance(version, int) or version > SCHEMA_VERSION or version < 1:
        raise CheckpointCorruptedError(
            f"checkpoint at {dirname} has schema_version={version!r}; this build "
            f"reads versions 1..{SCHEMA_VERSION}"
        )
    return manifest
