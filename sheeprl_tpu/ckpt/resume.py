"""Resume: `latest` resolution, verification, and manifest-format reading.

``checkpoint.resume_from=latest`` resolves the newest *manifest-valid*
checkpoint under the run root (``logs/runs/<root_dir>``, spanning every
timestamped run of the experiment), skipping ``.tmp`` partials from killed
writers and directories whose manifest fails to parse or whose shard files
are missing/short. A path to a run dir (or its ``checkpoint/`` dir, or
``<dir>/latest``) resolves within that directory instead. Pre-subsystem
orbax checkpoints (no manifest) are still accepted as a legacy fallback
with a warning, so old runs stay resumable.

:func:`read_checkpoint` is the loader ``Fabric.load`` dispatches to when it
sees a manifest: arrays are checksummed against the manifest before the
state is handed to the algorithms' resume path — a flipped bit fails loudly
here instead of as NaNs a thousand updates later.
"""

from __future__ import annotations

import os
import re
import warnings
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from sheeprl_tpu.ckpt.manifest import (
    MANIFEST_NAME,
    CheckpointCorruptedError,
    array_crc32,
    decode_array,
    read_manifest,
    unflatten_tree,
)
from sheeprl_tpu.ckpt.writer import TMP_SUFFIX

__all__ = [
    "is_manifest_checkpoint",
    "read_checkpoint",
    "resolve_latest",
    "resolve_resume_from",
    "validate_checkpoint",
]

_CKPT_DIR_RE = re.compile(r"^ckpt_(\d+)(?:_(\d+))?$")


def is_manifest_checkpoint(path: str) -> bool:
    return os.path.isfile(os.path.join(path, MANIFEST_NAME))


def _rank_sibling(path: str, rank: int) -> str:
    """``.../ckpt_<step>_<r>`` → the same step's dir for ``rank``."""
    head, name = os.path.split(os.path.abspath(path))
    m = _CKPT_DIR_RE.match(name)
    if m and m.group(2) is not None:
        return os.path.join(head, f"ckpt_{m.group(1)}_{rank}")
    return os.path.join(head, name)


# -- validation --------------------------------------------------------------


def validate_checkpoint(path: str, deep: bool = False) -> Dict[str, Any]:
    """Validate a manifest checkpoint dir; returns the manifest or raises.

    Quick mode checks the manifest parses and every referenced shard exists
    with its recorded byte size; ``deep`` additionally checksums every array.
    """
    manifest = read_manifest(path)
    for fname, nbytes in (manifest.get("files") or {}).items():
        fpath = os.path.join(path, fname)
        if not os.path.isfile(fpath) or os.path.getsize(fpath) != nbytes:
            raise CheckpointCorruptedError(
                f"checkpoint shard {fname} at {path} is missing or truncated"
            )
    if deep:
        read_checkpoint(path, verify=True)
    return manifest


# -- reading -----------------------------------------------------------------


def _load_npz(path: str) -> Dict[str, np.ndarray]:
    try:
        with np.load(path, allow_pickle=False) as z:
            return {k: z[k] for k in z.files}
    except FileNotFoundError:
        raise
    except Exception as exc:
        raise CheckpointCorruptedError(f"unreadable checkpoint shard {path}: {exc}") from exc


def _read_rb(path: str, section: Dict[str, Any], verify: bool) -> Any:
    kind = section.get("kind")
    if kind == "env_sliced":
        per_env: List[Dict[str, np.ndarray]] = []
        for shard in section["shards"]:
            arrays = _load_npz(os.path.join(path, shard["file"]))
            env: Dict[str, np.ndarray] = {}
            for k, meta in shard["arrays"].items():
                stored = arrays[meta["key"]]
                if verify and array_crc32(stored) != meta["crc32"]:
                    raise CheckpointCorruptedError(
                        f"checksum mismatch for buffer key {k!r} in {shard['file']}"
                    )
                env[k] = decode_array(stored, meta)
            per_env.append(env)
        keys = list(per_env[0]) if per_env else []
        return {
            "buffer": {k: np.stack([env[k] for env in per_env], axis=1) for k in keys},
            "pos": int(section.get("pos", 0)),
            "full": bool(section.get("full", False)),
        }
    if kind == "per_buffer":
        subs = []
        for shard in section["shards"]:
            arrays = _load_npz(os.path.join(path, shard["file"]))
            subs.append(
                unflatten_tree(shard["tree"], arrays, verify=verify, where=shard["file"])
            )
        return {section.get("container", "buffers"): subs}
    if kind == "tree":
        arrays = _load_npz(os.path.join(path, section["file"]))
        return unflatten_tree(section["tree"], arrays, verify=verify, where=section["file"])
    raise CheckpointCorruptedError(f"unknown replay-buffer shard kind {kind!r} at {path}")


def read_checkpoint(path: str, rank: int = 0, verify: bool = True) -> Dict[str, Any]:
    """Load a manifest-format checkpoint into a nested host pytree.

    The model state comes from ``path`` (or, when ``path`` is a non-zero
    rank's buffer-only dir, its rank-0 sibling); replay-buffer shards come
    from the calling rank's own sibling dir when it exists, surfacing under
    the ``"rb"`` key like the embedded legacy layout did.
    """
    path = os.path.abspath(path)
    manifest = read_manifest(path)

    state_manifest, state_dir = manifest, path
    if manifest.get("state") is None and manifest.get("rank", 0) != 0:
        sibling = _rank_sibling(path, 0)
        if os.path.isdir(sibling):
            state_manifest, state_dir = read_manifest(sibling), sibling

    out: Dict[str, Any] = {}
    section = state_manifest.get("state")
    if section is not None:
        arrays = _load_npz(os.path.join(state_dir, section["file"]))
        restored = unflatten_tree(
            section["tree"], arrays, verify=verify, where=section["file"]
        )
        if not isinstance(restored, dict):
            raise CheckpointCorruptedError(
                f"checkpoint state at {state_dir} is not a mapping"
            )
        out.update(restored)

    rb_manifest, rb_dir = manifest, path
    if rank != 0:
        sibling = _rank_sibling(path, rank)
        if sibling != path and os.path.isdir(sibling):
            rb_manifest, rb_dir = read_manifest(sibling), sibling
    if rb_manifest.get("rb") is not None:
        out["rb"] = _read_rb(rb_dir, rb_manifest["rb"], verify)
    return out


# -- `latest` resolution -----------------------------------------------------


def _candidates(root: str) -> List[Tuple[int, int, str]]:
    """(step, rank, path) for every final ckpt dir under ``root``."""
    found: List[Tuple[int, int, str]] = []
    for dirpath, dirnames, _files in os.walk(root):
        for name in list(dirnames):
            if name.endswith(TMP_SUFFIX):
                dirnames.remove(name)  # never descend into partials
                continue
            m = _CKPT_DIR_RE.match(name)
            if m:
                found.append(
                    (int(m.group(1)), int(m.group(2) or 0), os.path.join(dirpath, name))
                )
    return found


def resolve_latest(root: str, rank: int = 0) -> Optional[str]:
    """Newest manifest-valid checkpoint dir under ``root`` (``None`` if none).

    ``.tmp`` partials are never considered; candidates with corrupt or
    incomplete manifests are skipped with a warning; manifest-less (legacy
    orbax) dirs are only used when no manifest checkpoint validates at all.
    """
    legacy: List[Tuple[int, int, str]] = []
    ranked = sorted(
        _candidates(root), key=lambda c: (c[0], c[1] == rank, c[2]), reverse=True
    )
    for step, r, path in ranked:
        if not is_manifest_checkpoint(path):
            legacy.append((step, r, path))
            continue
        try:
            manifest = validate_checkpoint(path)
            if manifest.get("state") is None:
                # buffer-only shard of a non-zero rank: resumable only if the
                # state-bearing rank-0 sibling of the same step is itself
                # valid (the run may have died between the two renames)
                sibling = _rank_sibling(path, 0)
                if sibling == path or validate_checkpoint(sibling).get("state") is None:
                    raise CheckpointCorruptedError(
                        "checkpoint carries no model state and no state-bearing "
                        "rank-0 sibling exists"
                    )
        except (CheckpointCorruptedError, FileNotFoundError, OSError) as exc:
            warnings.warn(f"skipping invalid checkpoint {path}: {exc}")
            continue
        return path
    if legacy:
        step, r, path = max(legacy, key=lambda c: (c[0], c[1] == rank, c[2]))
        warnings.warn(
            f"no manifest-valid checkpoint under {root}; falling back to the "
            f"newest legacy (pre-manifest) checkpoint {path} without verification"
        )
        return path
    return None


def resolve_resume_from(cfg) -> str:
    """Turn ``checkpoint.resume_from`` into a concrete checkpoint dir.

    Accepted forms: ``latest`` (search ``logs/runs/<root_dir>`` — every run
    of this experiment), ``<dir>/latest`` or a run/checkpoint directory
    (search within), or a concrete ``ckpt_*`` path (returned as-is after a
    quick validation when it carries a manifest).
    """
    resume_from = str(cfg.checkpoint.resume_from)
    search_root = None
    if resume_from == "latest":
        search_root = os.path.join("logs", "runs", str(cfg.root_dir))
    elif os.path.basename(resume_from.rstrip("/")) == "latest":
        search_root = os.path.dirname(resume_from.rstrip("/"))
    elif os.path.isdir(resume_from) and not _CKPT_DIR_RE.match(
        os.path.basename(resume_from.rstrip("/"))
    ):
        search_root = resume_from
    if search_root is not None:
        resolved = resolve_latest(search_root)
        if resolved is None:
            raise FileNotFoundError(
                f"checkpoint.resume_from={cfg.checkpoint.resume_from!r}: no "
                f"resumable checkpoint found under {os.path.abspath(search_root)}"
            )
        print(f"[ckpt] resume_from=latest resolved to {resolved}", flush=True)
        return resolved
    if is_manifest_checkpoint(resume_from):
        validate_checkpoint(resume_from)  # fail at the CLI, not mid-restore
    return resume_from
