"""Preemption capture: treat SIGTERM/SIGINT as "checkpoint now, then leave".

TPU preemption (and most cluster schedulers) delivers SIGTERM with a short
grace window. The handler only sets a flag — Python delivers signals on the
main thread between bytecodes, and the train loop is the one place that
knows the current step state — so the loop's next
:func:`~sheeprl_tpu.ckpt.manager.should_checkpoint` check returns True, the
algorithm writes an immediate final checkpoint, breaks out, and the CLI's
teardown drains the in-flight async save before the process exits cleanly.

A second signal means "actually stop": the original disposition is restored
and the default behavior re-raised, so a hung drain can still be killed
interactively.

Async env workers (envs/vector) cooperate with this path from both sides:
workers ignore SIGTERM/SIGINT so a process-group signal cannot kill an env
mid-drain, and the pool's ``close()`` consults :func:`preemption_requested`
to shrink its worker-join budget — the grace window is spent writing the
final checkpoint, not tearing down simulators.
"""

from __future__ import annotations

import signal
import threading
from typing import Dict, Optional

__all__ = [
    "install_preemption_handlers",
    "preemption_requested",
    "reset_preemption",
    "uninstall_preemption_handlers",
]

_REQUESTED = threading.Event()
_PREV_HANDLERS: Dict[int, object] = {}


def preemption_requested() -> bool:
    """True once SIGTERM/SIGINT asked the run to checkpoint and exit."""
    return _REQUESTED.is_set()


def reset_preemption() -> None:
    _REQUESTED.clear()


def _handler(signum: int, frame: Optional[object]) -> None:
    if _REQUESTED.is_set():
        # second signal: stop being graceful
        uninstall_preemption_handlers()
        if signum == signal.SIGINT:
            raise KeyboardInterrupt
        raise SystemExit(128 + signum)
    _REQUESTED.set()
    print(
        f"[ckpt] received signal {signum}: requesting a final checkpoint; "
        "the run will save and exit at the next update (signal again to "
        "stop immediately)",
        flush=True,
    )


def install_preemption_handlers(signals=(signal.SIGTERM, signal.SIGINT)) -> bool:
    """Install the capture handlers. Returns False (and stays uninstalled)
    off the main thread — signal.signal is main-thread-only."""
    if threading.current_thread() is not threading.main_thread():
        return False
    for signum in signals:
        if signum in _PREV_HANDLERS:
            continue
        try:
            _PREV_HANDLERS[signum] = signal.signal(signum, _handler)
        except (ValueError, OSError):  # non-main interpreter / exotic platform
            return False
    return True


def uninstall_preemption_handlers() -> None:
    for signum, prev in list(_PREV_HANDLERS.items()):
        try:
            if signal.getsignal(signum) is _handler:
                signal.signal(signum, prev)
        except (ValueError, OSError):
            pass
        _PREV_HANDLERS.pop(signum, None)
