"""The per-run checkpoint manager and the loop-facing helpers.

One :class:`CheckpointManager` exists per training run, built by the CLI
from the ``checkpoint`` config group (:func:`setup_checkpoint`, mirroring
the telemetry lifecycle). Algorithms never touch it directly: they dispatch
``fabric.call("on_checkpoint_*")`` exactly as before, and the
:class:`~sheeprl_tpu.utils.callback.CheckpointCallback` routes into
:func:`get_checkpoint_manager`.

Step-path contract of :meth:`CheckpointManager.save`:

1. snapshot the state pytree to host (``jax.device_get`` — the only device
   interaction, and the only part the step must pay for);
2. hand the snapshot to the :class:`~sheeprl_tpu.ckpt.saver.AsyncSaver`
   (waiting out at most one in-flight previous save — double buffering);
3. return. Serialization, fsync, atomic rename, and keep-policy GC all run
   on the writer thread.

The wall time of 1+2 is accounted as ``ckpt_blocked_ms`` in the run
telemetry — that number IS the checkpoint cost of the train step.

Keep-policy GC (``checkpoint.keep_last``) runs on the writer thread right
after its own rename, so it is serialized with every write and can never
delete a checkpoint that is still being produced; stale ``.tmp`` partials
from a previously killed process are swept on the same pass.

Only rank 0 writes the replicated model state; every rank writes its own
replay-buffer shards into its per-rank ``ckpt_<step>_<rank>`` directory
(host-local buffers are rank state, the model is not).
"""

from __future__ import annotations

import glob
import os
import re
import shutil
import time
import warnings
from typing import Any, Dict, Optional

from sheeprl_tpu.ckpt.preemption import (
    install_preemption_handlers,
    preemption_requested,
    reset_preemption,
    uninstall_preemption_handlers,
)
from sheeprl_tpu.ckpt.saver import AsyncSaver
from sheeprl_tpu.ckpt.writer import OLD_SUFFIX, TMP_SUFFIX, write_checkpoint
from sheeprl_tpu.obs.counters import add_ckpt_blocked_ms

__all__ = [
    "CheckpointManager",
    "get_checkpoint_manager",
    "setup_checkpoint",
    "should_checkpoint",
    "teardown_checkpoint",
    "warn_checkpoint_rounding",
]

_STEP_RE = re.compile(r"ckpt_(\d+)")

_ACTIVE: Optional["CheckpointManager"] = None
_FALLBACK: Optional["CheckpointManager"] = None


class CheckpointManager:
    def __init__(
        self,
        async_save: bool = True,
        keep_last: Optional[int] = None,
        retries: int = 3,
        backoff_s: float = 0.5,
        algo: Optional[str] = None,
        config_hash: Optional[str] = None,
    ):
        self.async_save = bool(async_save)
        self.keep_last = keep_last
        self.algo = algo
        self.config_hash = config_hash
        self._saver = AsyncSaver(retries=retries, backoff_s=backoff_s)

    # -- persistence --------------------------------------------------------

    def save(
        self,
        ckpt_path: str,
        state: Optional[Dict[str, Any]],
        rb_state: Any = None,
        fabric: Any = None,
        keep_last: Optional[int] = None,
        sync: Optional[bool] = None,
        sharding_meta: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Snapshot ``state``/``rb_state`` and persist them as ``ckpt_path``.

        ``keep_last`` overrides the manager policy (callback-level knob);
        ``sync`` forces a synchronous write (final/preemption saves drain
        anyway, so they can stay async — this is for callers that must see
        write errors inline). ``sharding_meta`` (a ``ShardingPlan.describe``
        dict) is recorded in the manifest; the state itself is gathered to
        full host arrays below, so restores re-spec freely.
        """
        import jax

        import numpy as np

        t0 = time.perf_counter()
        rank = int(fabric.global_rank) if fabric is not None else 0
        world_size = int(fabric.world_size) if fabric is not None else 1
        if state is not None:
            # Model-sharded leaves on a multi-host mesh are not fully
            # addressable: device_get alone cannot materialize them, so they
            # are gathered across processes first (every rank participates —
            # this runs outside the rank-0 guard below on purpose). On a
            # single-process mesh every array is addressable and this is a
            # no-op.
            def _gather(x):
                if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
                    from jax.experimental import multihost_utils

                    return np.asarray(multihost_utils.process_allgather(x))
                return x

            state = jax.tree_util.tree_map(_gather, state)
        # The step-path snapshot. device_get alone is NOT a snapshot: on the
        # CPU backend it returns zero-copy views of the XLA buffers
        # (owndata=False), and a donated train step — or the entrypoint
        # frame's teardown — can rewrite that memory while the writer thread
        # is still serializing, corrupting the checkpoint after its checksums
        # were computed. Leaves that already own their memory (TPU/GPU
        # device_get output, host counters) are the snapshot and are not
        # copied again — on a big model that second copy would double the
        # step-path blocked time for nothing.
        def _own(x):
            if isinstance(x, np.ndarray) and x.flags.owndata:
                return x
            return np.array(x, copy=True)

        host_state = (
            jax.tree_util.tree_map(_own, jax.device_get(state))
            if (state is not None and rank == 0)
            else None
        )
        m = _STEP_RE.search(os.path.basename(ckpt_path))
        step = int(m.group(1)) if m else None
        keep = self.keep_last if keep_last is None else keep_last
        ckpt_path = os.path.abspath(ckpt_path)

        def _write() -> int:
            nbytes = write_checkpoint(
                ckpt_path,
                host_state,
                rb_state,
                step=step,
                rank=rank,
                world_size=world_size,
                algo=self.algo,
                config_hash=self.config_hash,
                sharding=sharding_meta,
            )
            self._prune(os.path.dirname(ckpt_path), rank, keep)
            return nbytes

        self._saver.submit(
            _write,
            label=os.path.basename(ckpt_path),
            sync=(not self.async_save) if sync is None else sync,
        )
        add_ckpt_blocked_ms((time.perf_counter() - t0) * 1000.0)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait for the in-flight async save to land (preemption/teardown)."""
        return self._saver.drain(timeout)

    @property
    def degraded(self) -> bool:
        return self._saver.degraded

    # -- keep-policy GC (runs on the writer thread, post-rename) ------------

    @staticmethod
    def _owned_step(name: str, rank: int) -> Optional[int]:
        """Step number when ``name`` is a ckpt dir THIS rank owns (its own
        ``ckpt_<step>_<rank>``, plus legacy un-suffixed dirs on rank 0)."""
        m = re.fullmatch(r"ckpt_(\d+)_(\d+)", name)
        if m:
            return int(m.group(1)) if int(m.group(2)) == rank else None
        if rank == 0:
            m = re.fullmatch(r"ckpt_(\d+)", name)
            if m:
                return int(m.group(1))
        return None

    def _prune(self, ckpt_dir: str, rank: int, keep_last: Optional[int]) -> None:
        if not os.path.isdir(ckpt_dir):
            return
        # stale partials: any of THIS rank's .tmp/.old dirs belongs to a dead
        # writer — the live one (this thread) already renamed its own. Other
        # ranks' .tmp dirs may be their in-flight writes; never touch them.
        for suffix in (TMP_SUFFIX, OLD_SUFFIX):
            for leftover in glob.glob(os.path.join(ckpt_dir, f"ckpt_*{suffix}")):
                name = os.path.basename(leftover)[: -len(suffix)]
                if self._owned_step(name, rank) is not None:
                    shutil.rmtree(leftover, ignore_errors=True)
        if not keep_last:
            return
        owned = []
        for path in glob.glob(os.path.join(ckpt_dir, "ckpt_*")):
            name = os.path.basename(path)
            if name.endswith(TMP_SUFFIX):
                continue
            step = self._owned_step(name, rank)
            if step is not None:
                owned.append((step, path))
        for _step, path in sorted(owned)[: -int(keep_last)]:
            shutil.rmtree(path, ignore_errors=True)


# -- run lifecycle (CLI-owned, telemetry-style) ------------------------------


def get_checkpoint_manager() -> CheckpointManager:
    """The run's manager; outside a CLI run, a process-wide default (async
    on, no keep policy) so direct callback use still gets the full pipeline."""
    global _FALLBACK
    if _ACTIVE is not None:
        return _ACTIVE
    if _FALLBACK is None:
        _FALLBACK = CheckpointManager()
    return _FALLBACK


def setup_checkpoint(cfg) -> CheckpointManager:
    """Build and activate the run manager from a composed config; installs
    the preemption handlers (main thread only)."""
    global _ACTIVE
    ccfg = cfg.get("checkpoint", {}) if hasattr(cfg, "get") else {}
    config_hash = None
    try:
        import hashlib

        from sheeprl_tpu.config.engine import to_yaml

        config_hash = hashlib.sha256(to_yaml(cfg).encode()).hexdigest()[:16]
    except Exception:  # pragma: no cover - hash is informational
        pass
    algo = None
    try:
        algo = str(cfg.algo.name)
    except AttributeError:
        pass
    _ACTIVE = CheckpointManager(
        async_save=bool(ccfg.get("async_save", True)),
        keep_last=ccfg.get("keep_last", None),
        retries=int(ccfg.get("write_retries", 3)),
        backoff_s=float(ccfg.get("write_backoff_s", 0.5)),
        algo=algo,
        config_hash=config_hash,
    )
    # a previous in-process run (multirun job, test) may have been preempted;
    # this run starts fresh — its own handlers are (re)installed below
    reset_preemption()
    install_preemption_handlers()
    return _ACTIVE


def teardown_checkpoint(drain_timeout: Optional[float] = 300.0) -> None:
    """Drain in-flight saves and deactivate (idempotent; CLI ``finally``)."""
    global _ACTIVE
    manager, _ACTIVE = _ACTIVE, None
    for m in (manager, _FALLBACK):
        if m is not None and not m.drain(drain_timeout):
            warnings.warn("a checkpoint write was still in flight after the drain timeout")
    uninstall_preemption_handlers()


# -- loop helpers (the only surface the 17 entrypoints see) ------------------


def should_checkpoint(
    cfg, policy_step: int, last_checkpoint: int, update: int, num_updates: int
) -> bool:
    """The per-update checkpoint gate: the reference cadence
    (``checkpoint.every`` policy steps, plus ``save_last`` on the final
    update) extended with preemption capture — a SIGTERM/SIGINT forces an
    immediate save regardless of cadence."""
    checkpointing_enabled = cfg.checkpoint.every > 0 or cfg.checkpoint.save_last
    return (
        (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
        or (update == num_updates and cfg.checkpoint.save_last)
        # preemption forces an immediate save — but not for runs that turned
        # checkpointing off entirely (benchmarks, throwaway probes)
        or (checkpointing_enabled and preemption_requested())
    )


def warn_checkpoint_rounding(cfg, policy_steps_per_update: int) -> None:
    """The (formerly copy-pasted-per-algo) ``checkpoint.every`` rounding
    warning: saves happen at update boundaries, so a non-multiple cadence
    rounds up to the next one."""
    if cfg.checkpoint.every % policy_steps_per_update != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_update value ({policy_steps_per_update}), so "
            "the checkpoint will be saved at the nearest greater multiple of the "
            "policy_steps_per_update value."
        )
