"""Fault-tolerant checkpoint subsystem.

Replaces the inline synchronous ``fabric.save`` (orbax pickle) that used to
run inside every train loop with a production-style checkpoint pipeline
(t5x/Orbax async checkpointing; Check-N-Run's snapshot/persist split):

- :mod:`~sheeprl_tpu.ckpt.saver` — the step path only snapshots the state
  pytree to host (``jax.device_get``); serialization and disk writes happen
  on a background thread with double-buffering (at most one save in flight,
  a new request waits instead of stacking), a bounded-retry/backoff wrapper
  around filesystem errors, and a degrade-to-synchronous fallback;
- :mod:`~sheeprl_tpu.ckpt.manifest` + :mod:`~sheeprl_tpu.ckpt.writer` — an
  atomic, verified on-disk layout: everything is written into
  ``ckpt_<step>.tmp/`` (npz shards, per-env replay-buffer shards, then a
  manifest with per-array shapes/dtypes/checksums, config hash and schema
  version, all fsynced) and renamed to final last, so a killed writer can
  never produce a checkpoint that resume will half-load;
- :mod:`~sheeprl_tpu.ckpt.preemption` — SIGTERM/SIGINT (the TPU preemption
  notice) requests an immediate final checkpoint from the train loop, the
  in-flight save is drained, and the run exits cleanly;
- :mod:`~sheeprl_tpu.ckpt.resume` — ``checkpoint.resume_from=latest``
  resolves the newest *manifest-valid* checkpoint in the run dir (skipping
  ``.tmp`` partials and corrupt manifests) and checksums arrays before the
  state reaches the algorithms' resume path.

Algorithms keep dispatching through ``fabric.call("on_checkpoint_*")`` — the
:class:`~sheeprl_tpu.utils.callback.CheckpointCallback` routes into the
:class:`~sheeprl_tpu.ckpt.manager.CheckpointManager` configured here by the
CLI (``checkpoint.async_save`` / ``checkpoint.keep_last`` /
``checkpoint.write_retries`` / ``checkpoint.write_backoff_s``). Keep-policy
GC lives on the manager's writer thread, serialized with the writes it could
otherwise race. The step-path cost of every save is visible in telemetry as
the ``ckpt_blocked_ms`` / ``ckpt_write_ms`` / ``ckpt_bytes`` counters
(``sheeprl_tpu/obs/``).
"""

from sheeprl_tpu.ckpt.manager import (
    CheckpointManager,
    get_checkpoint_manager,
    setup_checkpoint,
    should_checkpoint,
    teardown_checkpoint,
    warn_checkpoint_rounding,
)
from sheeprl_tpu.ckpt.manifest import (
    SCHEMA_VERSION,
    CheckpointCorruptedError,
)
from sheeprl_tpu.ckpt.preemption import (
    install_preemption_handlers,
    preemption_requested,
    reset_preemption,
    uninstall_preemption_handlers,
)
from sheeprl_tpu.ckpt.resume import (
    is_manifest_checkpoint,
    read_checkpoint,
    resolve_latest,
    resolve_resume_from,
    validate_checkpoint,
)

__all__ = [
    "SCHEMA_VERSION",
    "CheckpointCorruptedError",
    "CheckpointManager",
    "get_checkpoint_manager",
    "install_preemption_handlers",
    "is_manifest_checkpoint",
    "preemption_requested",
    "read_checkpoint",
    "reset_preemption",
    "resolve_latest",
    "resolve_resume_from",
    "setup_checkpoint",
    "should_checkpoint",
    "teardown_checkpoint",
    "uninstall_preemption_handlers",
    "validate_checkpoint",
    "warn_checkpoint_rounding",
]
