"""Partitioned host replay: N single-writer ring shards behind one facade.

The Reverb shape (Cassirer et al., 2021) on one host: each plane player
owns a shard (a plain :class:`~sheeprl_tpu.data.buffers.ReplayBuffer` whose
env columns are that player's env slice), so writers never contend on a
ring position, and the learner samples across shards through a single
facade that keeps the ``ReplayBuffer`` surface (``add`` / ``sample`` /
``seed`` / ``state_dict`` / ``bind_write_lock`` / ``to_device`` via the
staging facade).

Cross-shard planning: a burst of ``total`` rows is apportioned over shards
**proportional to shard fill** (valid rows × env columns) with
largest-remainder rounding — deterministic, no rng draw — then each shard's
slice is planned by the active :class:`~sheeprl_tpu.replay.strategies.
SamplingStrategy` *at the shard's own plan chokepoint* (staleness ages
observed per shard, PR-9 lineage intact) and the gathered rows are
interleaved by a facade-rng permutation so no gradient step in a multi-step
burst sees a shard-contiguous block.

Determinism contract: ``shards=1`` with the uniform strategy never
constructs this facade at all (``make_replay_buffer`` returns the plain
buffer), so the single-shard path is bitwise the pre-sharding code by
construction. When a facade IS constructed with one shard (a non-uniform
strategy), ``seed(s)`` still seeds shard 0 with ``s`` itself; with N
shards, shard ``i`` gets ``s + i`` and the facade's interleave rng gets
``s + n_shards`` (the ``EnvIndependentReplayBuffer`` offset idiom, so no
two streams share a seed).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.obs.counters import set_replay_shard_fill
from sheeprl_tpu.replay.strategies import SamplingStrategy, UniformStrategy

__all__ = ["ShardedReplay", "apportion_by_fill"]


def apportion_by_fill(total: int, weights: Sequence[float]) -> List[int]:
    """Split ``total`` draws proportional to ``weights`` (largest-remainder
    rounding, ties to the lowest index) — deterministic so the cross-shard
    plan consumes no rng. Zero-weight entries get nothing."""
    weights = [max(float(w), 0.0) for w in weights]
    wsum = sum(weights)
    if total <= 0:
        return [0] * len(weights)
    if wsum <= 0.0:
        raise ValueError("No shard holds data to sample from")
    quotas = [total * w / wsum for w in weights]
    counts = [int(q) for q in quotas]
    short = total - sum(counts)
    # hand the leftover draws to the largest fractional remainders
    order = sorted(range(len(weights)), key=lambda i: (-(quotas[i] - counts[i]), i))
    for i in order[:short]:
        counts[i] += 1
    return counts


class ShardedReplay:
    """Facade over N single-writer replay shards with strategy sampling."""

    def __init__(
        self,
        shards: Sequence[ReplayBuffer],
        strategy: Optional[SamplingStrategy] = None,
    ):
        if not shards:
            raise ValueError("ShardedReplay needs at least one shard")
        self._shards: List[ReplayBuffer] = list(shards)
        self._strategy: SamplingStrategy = strategy or UniformStrategy()
        self._rng: np.random.Generator = np.random.default_rng()
        # env-column offsets of each shard inside the global env axis
        self._env_offsets = np.cumsum([0] + [s.n_envs for s in self._shards])
        # last cross-shard plan in OUTPUT row order: (shard, t_idx, e_idx)
        self._last_plan: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None
        self._last_weights: Optional[np.ndarray] = None

    # -- surface parity with ReplayBuffer ---------------------------------

    @property
    def shards(self) -> List[ReplayBuffer]:
        return self._shards

    @property
    def n_shards(self) -> int:
        return len(self._shards)

    @property
    def strategy(self) -> SamplingStrategy:
        return self._strategy

    @property
    def needs_writeback(self) -> bool:
        return self._strategy.needs_writeback

    @property
    def buffer_size(self) -> int:
        return sum(s.buffer_size for s in self._shards)

    @property
    def n_envs(self) -> int:
        return sum(s.n_envs for s in self._shards)

    @property
    def full(self) -> bool:
        return all(s.full for s in self._shards)

    @property
    def empty(self) -> bool:
        return all(s.empty for s in self._shards)

    @property
    def is_memmap(self) -> bool:
        return all(s.is_memmap for s in self._shards)

    def __len__(self) -> int:
        return self.buffer_size

    def seed(self, seed: Optional[int] = None) -> None:
        n = len(self._shards)
        if n == 1:
            # single shard: the shard IS the old single buffer — same seed,
            # and the facade's interleave rng is never consulted (n==1 plans
            # skip the permutation entirely)
            self._shards[0].seed(seed)
            self._rng = np.random.default_rng(None if seed is None else seed + 1)
            return
        self._rng = np.random.default_rng(None if seed is None else seed + n)
        for i, s in enumerate(self._shards):
            s.seed(None if seed is None else seed + i)

    def bind_write_lock(self, lock: Any) -> None:
        for s in self._shards:
            s.bind_write_lock(lock)

    # -- ingest ------------------------------------------------------------

    def shard_for_env(self, env: int) -> Tuple[int, int]:
        """(shard index, local env column) of a global env column."""
        p = int(np.searchsorted(self._env_offsets, env, side="right")) - 1
        if p < 0 or p >= len(self._shards):
            raise ValueError(f"env column {env} outside [0, {self.n_envs})")
        return p, env - int(self._env_offsets[p])

    def add_shard(self, shard: int, data: Dict[str, np.ndarray], **kwargs: Any) -> None:
        """Route one writer's ``[T, shard_envs, ...]`` rows into its shard —
        the single-writer ingest path the replay plane uses (one plane
        player per shard, no cross-writer contention)."""
        self._shards[shard].add(data, **kwargs)
        if len(self._shards) > 1:
            new = self._shards[shard]
            fill = 1.0 if new.full else new._pos / new.buffer_size
            set_replay_shard_fill({str(shard): fill})

    def add(self, data: Dict[str, np.ndarray], validate_args: bool = False) -> None:
        """Whole-fleet ``[T, n_envs, ...]`` insert, split along the env axis
        by shard ownership (coupled single-collector algos)."""
        for p in range(len(self._shards)):
            lo, hi = int(self._env_offsets[p]), int(self._env_offsets[p + 1])
            self.add_shard(p, {k: np.asarray(v)[:, lo:hi] for k, v in data.items()},
                           validate_args=validate_args)

    def fills(self) -> List[float]:
        """Per-shard fill fraction (1.0 once a shard's ring has wrapped)."""
        out = []
        for s in self._shards:
            out.append(1.0 if s.full else (0.0 if s.empty else s._pos / s.buffer_size))
        return out

    def init_priorities_newest(self, shard: int, steps: int) -> None:
        """Mark the ``steps`` newest rows of ``shard`` max-priority — called
        by the replay plane right after an ingest so fresh transitions are
        sampled soon (the Ape-X commit-channel behavior)."""
        s = self._shards[shard]
        if steps <= 0:
            return
        write_len = min(int(steps), s.buffer_size)
        start = s._pos - write_len
        t_idx = np.arange(start, start + write_len) % s.buffer_size
        self._strategy.init_priorities(s, t_idx)

    # -- sampling ----------------------------------------------------------

    def _shard_weights(self, sample_next_obs: bool) -> List[float]:
        out = []
        for s in self._shards:
            if s.empty or (not s.full and s._pos == 0):
                out.append(0.0)
            else:
                out.append(len(s.valid_time_indices(sample_next_obs)) * float(s.n_envs))
        return out

    def plan_burst(
        self, total: int, sample_next_obs: bool = False
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Plan ``total`` rows across shards: fill-proportional apportionment,
        per-shard strategy plan (staleness observed at each shard's
        chokepoint), facade-rng interleave. Returns output-ordered
        ``(shard_ids, t_idx, e_idx)`` with e_idx LOCAL to each shard."""
        weights = self._shard_weights(sample_next_obs)
        counts = apportion_by_fill(total, weights)
        weighted = self._strategy.needs_writeback
        shard_ids = np.empty(total, np.int64)
        t_all = np.empty(total, np.int64)
        e_all = np.empty(total, np.int64)
        w_all = np.empty(total, np.float64) if weighted else None
        cursor = 0
        for p, count in enumerate(counts):
            if count == 0:
                continue
            t_idx, e_idx = self._strategy.plan(
                self._shards[p], count, sample_next_obs=sample_next_obs, n_samples=1
            )
            shard_ids[cursor : cursor + count] = p
            t_all[cursor : cursor + count] = t_idx
            e_all[cursor : cursor + count] = e_idx
            if weighted:
                # raw (unnormalized) importance weights, captured in this
                # shard's plan order so the permutation below keeps them
                # aligned row-for-row with the plan
                w_all[cursor : cursor + count] = self._strategy.weights(
                    self._shards[p], normalize=False
                )
            cursor += count
        if len(self._shards) > 1:
            perm = self._rng.permutation(total)
            shard_ids, t_all, e_all = shard_ids[perm], t_all[perm], e_all[perm]
            if weighted:
                w_all = w_all[perm]
        # normalize by the GLOBAL max so shards with different priority
        # scales stay comparable
        self._last_weights = (w_all / w_all.max()) if weighted else None
        return shard_ids, t_all, e_all

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        """``[n_samples, batch_size, ...]`` rows drawn across shards."""
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        total = batch_size * n_samples
        shard_ids, t_all, e_all = self.plan_burst(total, sample_next_obs)
        self._last_plan = (shard_ids, t_all, e_all)
        parts: Dict[str, np.ndarray] = {}
        for p in range(len(self._shards)):
            mask = shard_ids == p
            if not mask.any():
                continue
            rows = self._shards[p].gather_plan(
                t_all[mask], e_all[mask], sample_next_obs=sample_next_obs, clone=False
            )
            for k, v in rows.items():
                if k not in parts:
                    parts[k] = np.empty((total,) + v.shape[1:], v.dtype)
                parts[k][mask] = v
        return {
            k: v.reshape(n_samples, batch_size, *v.shape[1:]) for k, v in parts.items()
        }

    def last_weights(self) -> Optional[np.ndarray]:
        """Importance weights aligned with the last sampled burst's flat row
        order (``None`` for unweighted strategies)."""
        return self._last_weights

    def update_priorities(self, td_errors: np.ndarray) -> None:
        """Write the last burst's TD errors back through the strategy, routed
        to each row's owning shard (flat row order of the last plan)."""
        if self._last_plan is None:
            raise RuntimeError("update_priorities called before any sample")
        shard_ids, t_all, e_all = self._last_plan
        td = np.asarray(td_errors).reshape(-1)
        if len(td) != len(shard_ids):
            raise ValueError(
                f"td_errors has {len(td)} rows but the last plan drew {len(shard_ids)}"
            )
        for p in range(len(self._shards)):
            mask = shard_ids == p
            if mask.any():
                self._strategy.update_priorities(
                    self._shards[p], t_all[mask], e_all[mask], td[mask]
                )

    def sample_tensors(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        dtype: Optional[Any] = None,
        device: Optional[Any] = None,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        from sheeprl_tpu.data.buffers import to_device

        batch = self.sample(batch_size, sample_next_obs, clone, n_samples, **kwargs)
        return to_device(batch, dtype=dtype, device=device)

    # -- checkpointing ------------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        return {"shards": [s.state_dict() for s in self._shards]}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        saved = state["shards"]
        if len(saved) != len(self._shards):
            raise ValueError(
                f"Checkpoint has {len(saved)} replay shards but the run is configured "
                f"with {len(self._shards)} — replay.shards must match to resume"
            )
        for s, sd in zip(self._shards, saved):
            s.load_state_dict(sd)
