"""Sharded replay plane (ROADMAP item 4, howto/replay_plane.md).

Multi-writer partitioned replay in the Reverb/Ape-X mold, grown from the
repo's own pieces: plane players (PR 7) each own one single-writer host
ring shard, a cross-shard planner draws bursts proportional to shard fill
while preserving the PR-9 staleness lineage, sampling strategies (uniform /
prioritize-ends / TD-priority with importance weights) are a first-class
registry, and the single-group device ring can *adopt* slab rows straight
to HBM (``bytes_staged_h2d`` ≈ payload, not 2×).

``replay.shards=1`` with the uniform strategy is bitwise the pre-sharding
path — :func:`make_replay_buffer` returns the plain ``ReplayBuffer`` and no
facade is involved.
"""

from sheeprl_tpu.replay.factory import make_replay_buffer, replay_config, shard_env_split
from sheeprl_tpu.replay.plane import ReplayPlane
from sheeprl_tpu.replay.sharded import ShardedReplay, apportion_by_fill
from sheeprl_tpu.replay.strategies import (
    PrioritizeEndsStrategy,
    SamplingStrategy,
    TDPriorityStrategy,
    UniformStrategy,
    available_strategies,
    get_strategy,
    make_strategy,
    register_strategy,
)

__all__ = [
    "PrioritizeEndsStrategy",
    "ReplayPlane",
    "SamplingStrategy",
    "ShardedReplay",
    "TDPriorityStrategy",
    "UniformStrategy",
    "apportion_by_fill",
    "available_strategies",
    "get_strategy",
    "make_replay_buffer",
    "make_strategy",
    "register_strategy",
    "replay_config",
    "shard_env_split",
]
