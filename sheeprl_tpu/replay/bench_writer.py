"""Synthetic shard-writer entry for ``tools/bench_replay.py``.

``run_writer`` speaks the full player protocol of the execution plane
(:mod:`sheeprl_tpu.plane.worker` — acquire slab, fill rows, ``emit``) but
generates trajectory rows synthetically with a configurable simulated
env-step latency instead of stepping real environments. That makes the
replay bench honest on a small host: each writer is *latency-bound* the way
a real env fleet is (the wall time is sleeps, not compute), so running N
writer processes measures the replay plane's ability to overlap N
collection streams — the architecture claim — rather than raw CPU
parallelism the host may not have.

The bench harness launches this entry by dotted name
(``sheeprl_tpu.replay.bench_writer:run_writer``) through the same
``ProcessPlane`` supervisor the SAC learner uses, so slab transport,
credited-slot backpressure, and respawn behavior are all the production
code paths.

Knobs (read from ``cfg.bench_replay``, all optional):

- ``obs_dim`` / ``act_dim`` — synthetic row widths (defaults 8 / 2);
- ``step_latency_s`` — simulated per-env-step latency (default 1 ms);
- ``payload_fill`` — when true, rows carry deterministic non-zero payloads
  (seeded per player) so adoption-parity checks can compare bytes.
"""

from __future__ import annotations

import time
from typing import Dict

import numpy as np

__all__ = ["bench_slab_example", "run_writer"]


def bench_slab_example(
    capacity: int, n_envs: int, obs_dim: int, act_dim: int
) -> Dict[str, np.ndarray]:
    """Example arrays fixing the synthetic trajectory-slab layout (the SAC
    transition layout, minus next-obs — the bench samples with
    ``sample_next_obs=True`` semantics where relevant)."""
    return {
        "observations": np.zeros((capacity, n_envs, obs_dim), np.float32),
        "actions": np.zeros((capacity, n_envs, act_dim), np.float32),
        "rewards": np.zeros((capacity, n_envs, 1), np.float32),
        "dones": np.zeros((capacity, n_envs, 1), np.float32),
    }


def run_writer(ctx) -> None:
    """Produce updates ``[ctx.start_update, num_updates]`` of synthetic
    transition rows, one committed slab per burst, sleeping the configured
    env-step latency per step."""
    from sheeprl_tpu.plane.protocol import burst_plan

    cfg = ctx.cfg
    bench = dict(cfg.get("bench_replay", {}) or {})
    n_envs = int(ctx.n_envs)
    obs_dim = int(bench.get("obs_dim", 8))
    act_dim = int(bench.get("act_dim", 2))
    latency_s = float(bench.get("step_latency_s", 1e-3))
    payload_fill = bool(bench.get("payload_fill", True))
    rng = np.random.default_rng(int(cfg.seed) + 104729 * (int(ctx.player_idx) + 1))

    update = int(ctx.start_update)
    while update <= ctx.num_updates and not ctx.stop.is_set() and not ctx.orphaned():
        n_act, _ = burst_plan(
            update, ctx.act_burst, ctx.learning_starts, ctx.num_updates
        )
        token, views = ctx.acquire_slab()
        for r in range(n_act):
            if latency_s > 0:
                time.sleep(latency_s)  # the simulated env step
            if payload_fill:
                views["observations"][r] = rng.random((n_envs, obs_dim), np.float32)
                views["actions"][r] = rng.random((n_envs, act_dim), np.float32)
                views["rewards"][r] = rng.random((n_envs, 1), np.float32)
                views["dones"][r] = 0.0
            ctx.beat()
        ctx.emit(token, views, update, n_act, 0, [])
        update += n_act
