"""The one place algo entrypoints get replay storage from.

Every entrypoint used to open-code its buffer (size arithmetic, memmap
directory layout, dreamer's sequential-vs-episode dispatch) — 16 sites with
the same five lines. ``make_replay_buffer`` centralizes them so the sharded
replay plane can slide under any off-policy algo without touching its loop,
and ``tools/lint_replay.py`` can forbid raw buffer construction in
``algos/`` wholesale.

Size semantics (exactly the historical per-site arithmetic):

- ``per_env=True`` (off-policy): ``cfg.buffer.size // n_envs`` rows per env
  column, ``dry_run_size`` under ``cfg.dry_run``, floored at ``min_size``.
- ``size=...`` (on-policy rollout storage): the caller's explicit row
  count, still floored at ``min_size``.

Sharding/strategy policy: only *sampled* transition storage
(``kind="transition"``, ``sampled=True``) participates in the replay plane.
``replay.shards>1`` partitions the env axis over N single-writer shards
(``shard_envs`` gives the per-shard env counts); a non-uniform
``replay.strategy`` wraps even a single shard in the
:class:`~sheeprl_tpu.replay.sharded.ShardedReplay` facade so the strategy
owns planning. ``shards=1`` + ``uniform`` returns the plain
:class:`~sheeprl_tpu.data.buffers.ReplayBuffer` — the pre-sharding object,
bitwise the old path. Sequence/episode storage ignores ``replay.strategy``
with a warning (the EpisodeBuffer's own ``prioritize_ends`` flag already
covers the episode case).
"""

from __future__ import annotations

import os
import warnings
from typing import Any, List, Optional, Sequence, Union

from sheeprl_tpu.data.buffers import (
    EnvIndependentReplayBuffer,
    EpisodeBuffer,
    ReplayBuffer,
    SequentialReplayBuffer,
)
from sheeprl_tpu.replay.sharded import ShardedReplay
from sheeprl_tpu.replay.strategies import make_strategy

__all__ = ["make_replay_buffer", "replay_config", "shard_env_split"]

AnyReplay = Union[
    ReplayBuffer, EpisodeBuffer, EnvIndependentReplayBuffer, ShardedReplay
]


def replay_config(cfg: Any) -> dict:
    """``cfg.replay`` as a plain dict (tolerant of configs predating the
    replay group)."""
    try:
        replay = cfg.get("replay", None)
    except AttributeError:
        replay = getattr(cfg, "replay", None)
    return dict(replay) if replay else {}


def shard_env_split(n_envs: int, n_shards: int) -> List[int]:
    """Per-shard env-column counts: the env axis split as evenly as possible
    (first ``n_envs % n_shards`` shards take one extra column) — the same
    split ``plane_env_split`` applies to players, so player p's slab columns
    are exactly shard p's env columns."""
    if n_shards <= 0:
        raise ValueError(f"'replay.shards' must be positive, got {n_shards}")
    if n_shards > n_envs:
        raise ValueError(
            f"'replay.shards' ({n_shards}) cannot exceed the env count ({n_envs})"
        )
    base, extra = divmod(n_envs, n_shards)
    return [base + (1 if i < extra else 0) for i in range(n_shards)]


def _memmap_dir(log_dir: Optional[str], rank: int) -> Optional[str]:
    if log_dir is None:
        return None
    return os.path.join(log_dir, "memmap_buffer", f"rank_{rank}")


def make_replay_buffer(
    cfg: Any,
    fabric: Any,
    log_dir: Optional[str],
    *,
    n_envs: int,
    kind: str = "transition",
    obs_keys: Sequence[str] = ("observations",),
    per_env: bool = True,
    size: Optional[int] = None,
    min_size: int = 1,
    dry_run_size: Optional[int] = None,
    sequence_length: Optional[int] = None,
    sampled: bool = True,
    shards: Optional[int] = None,
) -> AnyReplay:
    """Build the replay storage an entrypoint needs (see module docstring)."""
    if size is not None:
        base = int(size)
    elif bool(cfg.dry_run) and dry_run_size is not None:
        base = int(dry_run_size)
    else:
        base = int(cfg.buffer.size) // n_envs if per_env else int(cfg.buffer.size)
    buffer_size = max(base, int(min_size))
    memmap = bool(cfg.buffer.memmap)
    memmap_dir = _memmap_dir(log_dir, int(fabric.global_rank))
    replay_cfg = replay_config(cfg)
    strategy_name = str(replay_cfg.get("strategy", "uniform") or "uniform")
    if shards is None:
        # callers that pre-validate (the decoupled plane: shards must equal
        # num_players) pass shards explicitly; everyone else takes the config.
        # Rollout storage never participates in the replay plane, so a
        # configured shard count does not apply to it.
        shards = int(replay_cfg.get("shards", 1) or 1) if sampled else 1
    shards = int(shards)

    if kind == "dreamer":
        # dreamer_v2's historical cfg.buffer.type dispatch, error text intact
        buffer_type = str(cfg.buffer.get("type", "sequential")).lower()
        if buffer_type == "sequential":
            kind = "sequential"
        elif buffer_type == "episode":
            kind = "episode"
        else:
            raise ValueError(
                f"Unrecognized buffer type: must be one of `sequential` or `episode`, "
                f"received: {buffer_type}"
            )

    if kind in ("sequential", "episode") or not sampled:
        if sampled and strategy_name != "uniform":
            warnings.warn(
                f"replay.strategy={strategy_name!r} only applies to transition replay; "
                f"{kind!r} storage keeps uniform sampling "
                "(episode storage has its own buffer.prioritize_ends flag)",
                stacklevel=2,
            )
        if shards != 1:
            raise ValueError(
                f"replay.shards={shards} is only supported for sampled transition "
                f"replay, not {kind!r} storage"
            )

    if kind == "sequential":
        return EnvIndependentReplayBuffer(
            buffer_size,
            n_envs=n_envs,
            obs_keys=obs_keys,
            memmap=memmap,
            memmap_dir=memmap_dir,
            buffer_cls=SequentialReplayBuffer,
        )
    if kind == "episode":
        if sequence_length is None:
            raise ValueError("episode replay needs a 'sequence_length'")
        # historical episode sizing floors at the sequence length alone
        # (never min_size — that floor belongs to the sequential branch)
        return EpisodeBuffer(
            max(base, int(sequence_length)),
            sequence_length=int(sequence_length),
            n_envs=n_envs,
            obs_keys=obs_keys,
            prioritize_ends=bool(cfg.buffer.get("prioritize_ends", False)),
            memmap=memmap,
            memmap_dir=memmap_dir,
        )
    if kind != "transition":
        raise ValueError(
            f"Unknown replay kind {kind!r}: must be one of "
            "`transition`, `sequential`, `episode`, or `dreamer`"
        )

    if not sampled or (shards == 1 and strategy_name == "uniform"):
        # the pre-sharding object — rollout storage, or the bitwise
        # single-shard uniform path
        return ReplayBuffer(
            buffer_size,
            n_envs,
            obs_keys=obs_keys,
            memmap=memmap,
            memmap_dir=memmap_dir,
        )

    env_counts = shard_env_split(n_envs, shards)
    shard_bufs = []
    for p, shard_envs in enumerate(env_counts):
        shard_dir = (
            os.path.join(memmap_dir, f"shard_{p}")
            if (memmap_dir is not None and shards > 1)
            else memmap_dir
        )
        shard_bufs.append(
            ReplayBuffer(
                buffer_size,
                shard_envs,
                obs_keys=obs_keys,
                memmap=memmap,
                memmap_dir=shard_dir,
            )
        )
    return ShardedReplay(shard_bufs, strategy=make_strategy(replay_cfg))
