"""First-class replay sampling strategies (the Ape-X ingredient).

A :class:`SamplingStrategy` owns the *plan* step of a replay draw — which
``(t_idx, e_idx)`` pairs a burst reads — while the buffers keep owning
storage, gather, and the valid-window semantics. Strategies are registered
by name (:func:`register_strategy` / :func:`get_strategy`) and selected via
``cfg.replay.strategy``:

- ``uniform`` — delegates straight to ``ReplayBuffer.plan_transitions``:
  byte-for-byte the single-buffer planner, consuming the buffer's own rng
  stream in the same order (the ``replay.shards=1`` bitwise gate rides on
  this).
- ``prioritize_ends`` — the ``EpisodeBuffer`` end-bias generalized to flat
  transition rings: draw an offset uniformly over the age-ordered valid
  window and clamp it to the last valid start
  (:func:`sheeprl_tpu.data.buffers.end_biased_start` — the *same* function
  the EpisodeBuffer draw calls), so recent rows are over-sampled exactly the
  way episode tails are.
- ``td_priority`` — proportional prioritized replay (Schaul et al., 2016,
  as deployed by Ape-X): sampling probability ``p_i^alpha / sum p^alpha``
  with ``p_i = |td_i| + eps``, importance weights ``(N * P_i)^-beta``
  normalized by their max, and a post-train writeback channel
  (:meth:`TDPriorityStrategy.update_priorities`) that re-scores the rows
  the last plan drew. Unseen rows carry the running max priority so every
  transition is sampled at least once with high probability.

Every strategy observes the drawn rows' ages at the plan chokepoint
(``rb.observe_sample_ages`` — for uniform this happens inside
``plan_transitions``), preserving the PR-9 staleness lineage no matter how
the plan was produced.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from sheeprl_tpu.data.buffers import ReplayBuffer, end_biased_start
from sheeprl_tpu.obs.counters import add_replay_priority_updates

__all__ = [
    "SamplingStrategy",
    "UniformStrategy",
    "PrioritizeEndsStrategy",
    "TDPriorityStrategy",
    "available_strategies",
    "get_strategy",
    "make_strategy",
    "register_strategy",
]

_REGISTRY: Dict[str, Type["SamplingStrategy"]] = {}


def register_strategy(name: str):
    """Class decorator registering a strategy under ``name``."""

    def deco(cls: Type["SamplingStrategy"]) -> Type["SamplingStrategy"]:
        cls.name = name
        _REGISTRY[name] = cls
        return cls

    return deco


def available_strategies() -> List[str]:
    return sorted(_REGISTRY)


def get_strategy(name: str) -> Type["SamplingStrategy"]:
    try:
        return _REGISTRY[str(name)]
    except KeyError:
        raise ValueError(
            f"Unknown replay sampling strategy {name!r}: must be one of {sorted(_REGISTRY)}"
        ) from None


def make_strategy(replay_cfg: Optional[Dict[str, Any]]) -> "SamplingStrategy":
    """Build the strategy ``cfg.replay`` names (defaults to ``uniform``)."""
    replay_cfg = replay_cfg or {}
    name = str(replay_cfg.get("strategy", "uniform") or "uniform")
    cls = get_strategy(name)
    if name == "td_priority":
        prio = replay_cfg.get("priority", {}) or {}
        return cls(
            alpha=float(prio.get("alpha", 0.6)),
            beta=float(prio.get("beta", 0.4)),
            eps=float(prio.get("eps", 1e-6)),
        )
    return cls()


def _plan_envs(
    rng: np.random.Generator, n_envs: int, envs: Optional[Sequence[int]], total: int
) -> np.ndarray:
    """The env-column draw shared with ``plan_transitions`` (same order:
    time indices first, env columns second, off one rng stream)."""
    if envs is None:
        return rng.integers(0, n_envs, size=total)
    envs_arr = np.asarray(envs, dtype=np.int64)
    return envs_arr[rng.integers(0, len(envs_arr), size=total)]


class SamplingStrategy:
    """Plans which rows a replay burst reads; stateless unless prioritized."""

    name = "base"
    #: True when the training loop must write updated priorities back after
    #: each gradient burst — the staging facade then disables prefetch so the
    #: last plan always corresponds to the batch just trained on
    needs_writeback = False

    def plan(
        self,
        rb: ReplayBuffer,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        rng: Optional[np.random.Generator] = None,
        envs: Optional[Sequence[int]] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        raise NotImplementedError

    def weights(self, rb: ReplayBuffer, normalize: bool = True) -> Optional[np.ndarray]:
        """Importance weights aligned with ``rb``'s last plan (None when the
        strategy is unweighted). ``normalize=False`` returns the raw
        ``(N * P)^-beta`` values so a cross-shard caller can normalize by the
        global max instead of each shard's own."""
        return None

    def update_priorities(
        self, rb: ReplayBuffer, t_idx: np.ndarray, e_idx: np.ndarray, td_errors: np.ndarray
    ) -> None:
        """Post-train priority writeback (no-op unless prioritized)."""

    def init_priorities(self, rb: ReplayBuffer, t_idx: np.ndarray) -> None:
        """Mark freshly ingested time rows max-priority (no-op unless
        prioritized) — the commit-channel hook the replay plane calls after
        routing a slab into a shard."""


@register_strategy("uniform")
class UniformStrategy(SamplingStrategy):
    """Delegates to the buffer's own uniform planner — bitwise the current
    single-buffer path (same rng stream, same draw order)."""

    def plan(self, rb, batch_size, sample_next_obs=False, n_samples=1, rng=None, envs=None):
        return rb.plan_transitions(
            batch_size, sample_next_obs=sample_next_obs, n_samples=n_samples, rng=rng, envs=envs
        )


@register_strategy("prioritize_ends")
class PrioritizeEndsStrategy(SamplingStrategy):
    """EpisodeBuffer's end bias over a flat ring's age-ordered window.

    The EpisodeBuffer draw picks a window start uniformly over the *whole*
    episode and clamps to the last valid start, piling the tail's mass onto
    the newest eligible position. Here the "episode" is the ring's full
    age-ordered valid window: offsets draw via the identical
    :func:`end_biased_start` with ``length = len(window incl. the
    successor-less newest row)`` and ``upper = length - effective`` where
    ``effective = 1 + (1 if sample_next_obs else 0)`` — a transition is a
    length-1 sequence, plus its stored successor when requested.
    """

    def plan(self, rb, batch_size, sample_next_obs=False, n_samples=1, rng=None, envs=None):
        rng = rb._rng if rng is None else rng
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        if rb.empty or (not rb.full and rb._pos == 0):
            raise ValueError("No sample has been added to the buffer")
        # ordered over the FULL window (successor-less newest row included:
        # it is the clamped tail, like the last steps of an episode)
        ordered = rb.age_ordered_time_indices(sample_next_obs=False)
        length = len(ordered)
        effective = 1 + (1 if sample_next_obs else 0)
        upper = length - effective
        if upper < 0:
            raise RuntimeError(
                "You want to sample the next observations, but only one sample has been "
                "added to the buffer. Make sure that at least two samples are added."
            )
        total = batch_size * n_samples
        raw = rng.integers(0, length, size=total)
        t_idx = ordered[np.minimum(raw, upper)]
        e_idx = _plan_envs(rng, rb.n_envs, envs, total)
        rb.observe_sample_ages(t_idx)
        return t_idx, e_idx


@register_strategy("td_priority")
class TDPriorityStrategy(SamplingStrategy):
    """Proportional TD-error prioritization with importance weights.

    Per-buffer state (the ``[size, n_envs]`` priority table and the running
    max) is keyed on the buffer instance, so one strategy object serves
    every shard of a :class:`~sheeprl_tpu.replay.sharded.ShardedReplay`
    without the shards sharing priorities.
    """

    needs_writeback = True

    def __init__(self, alpha: float = 0.6, beta: float = 0.4, eps: float = 1e-6):
        if not 0.0 <= alpha:
            raise ValueError(f"'alpha' must be non-negative, got {alpha}")
        if not 0.0 <= beta:
            raise ValueError(f"'beta' must be non-negative, got {beta}")
        if eps <= 0.0:
            raise ValueError(f"'eps' must be positive, got {eps}")
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.eps = float(eps)
        # id(rb) -> (rb, priority table, running max); the strong buffer ref
        # pins the id so it cannot be recycled under us
        self._state: Dict[int, Tuple[ReplayBuffer, np.ndarray, float]] = {}
        # id(rb) -> (t_idx, e_idx, P_i, n_valid) of the last plan — what
        # weights() aligns with and update_priorities() falls back to
        self._last: Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray, int]] = {}

    def _table(self, rb: ReplayBuffer) -> np.ndarray:
        key = id(rb)
        if key not in self._state:
            self._state[key] = (rb, np.zeros((rb.buffer_size, rb.n_envs), np.float64), 1.0)
        return self._state[key][1]

    def _max_prio(self, rb: ReplayBuffer) -> float:
        self._table(rb)
        return self._state[id(rb)][2]

    def plan(self, rb, batch_size, sample_next_obs=False, n_samples=1, rng=None, envs=None):
        rng = rb._rng if rng is None else rng
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        if rb.empty or (not rb.full and rb._pos == 0):
            raise ValueError("No sample has been added to the buffer")
        valid = rb.valid_time_indices(sample_next_obs)
        if len(valid) == 0:
            raise RuntimeError(
                "You want to sample the next observations, but only one sample has been "
                "added to the buffer. Make sure that at least two samples are added."
            )
        table = self._table(rb)
        env_cols = (
            np.arange(rb.n_envs, dtype=np.int64)
            if envs is None
            else np.asarray(envs, dtype=np.int64)
        )
        prio = table[np.ix_(valid, env_cols)]  # [L, E]
        prio = np.where(prio > 0.0, prio, self._max_prio(rb))
        scaled = prio.ravel() ** self.alpha
        probs = scaled / scaled.sum()
        total = batch_size * n_samples
        n_cols = len(env_cols)
        flat = rng.choice(len(probs), size=total, p=probs)
        t_idx = valid[flat // n_cols]
        e_idx = env_cols[flat % n_cols]
        self._last[id(rb)] = (t_idx, e_idx, probs[flat], len(probs))
        rb.observe_sample_ages(t_idx)
        return t_idx, e_idx

    def weights(self, rb, normalize=True):
        last = self._last.get(id(rb))
        if last is None:
            return None
        _, _, p_sel, n_valid = last
        w = (n_valid * p_sel) ** (-self.beta)
        return w / w.max() if normalize else w

    def update_priorities(self, rb, t_idx, e_idx, td_errors):
        t_idx = np.asarray(t_idx, dtype=np.int64).reshape(-1)
        e_idx = np.asarray(e_idx, dtype=np.int64).reshape(-1)
        td = np.abs(np.asarray(td_errors, dtype=np.float64).reshape(-1)) + self.eps
        if not (len(t_idx) == len(e_idx) == len(td)):
            raise ValueError(
                f"Priority writeback shapes disagree: {len(t_idx)} rows, "
                f"{len(e_idx)} env columns, {len(td)} td errors"
            )
        table = self._table(rb)
        table[t_idx, e_idx] = td
        key = id(rb)
        rb_ref, tbl, max_prio = self._state[key]
        self._state[key] = (rb_ref, tbl, max(max_prio, float(td.max())) if len(td) else max_prio)
        add_replay_priority_updates(len(td))

    def init_priorities(self, rb, t_idx):
        t_idx = np.asarray(t_idx, dtype=np.int64).reshape(-1)
        if len(t_idx) == 0:
            return
        table = self._table(rb)
        table[t_idx, :] = self._max_prio(rb)
