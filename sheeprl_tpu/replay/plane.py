"""The replay plane's ingest bridge: plane players → partitioned shards.

:class:`ReplayPlane` composes an already-built execution plane
(:class:`~sheeprl_tpu.plane.supervisor.ProcessPlane` or ``LocalPlane``) with
a :class:`~sheeprl_tpu.replay.sharded.ShardedReplay` whose shard partition
mirrors the plane's env split — player ``p``'s slab columns are exactly
shard ``p``'s env columns (``shard_env_split`` == ``plane_env_split`` when
``replay.shards == plane.num_players``). That makes every shard
single-writer by construction: slabs from player ``p`` only ever land in
shard ``p``, so ingest needs no cross-shard coordination and the learner's
``concatenate``-then-``add`` full-width copy disappears.

Ingest also carries the PR-9 staleness lineage *per shard*: each player's
slab commit stamp is re-armed through
:func:`~sheeprl_tpu.obs.dist.staleness.stamp_next_add` right before that
shard's ``add``, so sample ages are measured from each shard's own
collection time instead of whichever handle happened to be received last
(the single-buffer path's last-stamp-wins behavior).

Writer-restart observability: the supervisor already fires a
``plane_player_restart`` flight trigger and counter when it respawns a
player. When that player is a replay *writer* (a shard owner), losing it
also stalls a shard's fill, so :meth:`ReplayPlane.ingest` watches the
plane's restart ledger and fires a ``replay_writer_restart`` flight trigger
carrying the shard's fill at the moment of loss.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sheeprl_tpu.obs import get_telemetry
from sheeprl_tpu.obs.dist import staleness as _staleness

__all__ = ["ReplayPlane"]


class ReplayPlane:
    """Route per-player trajectory slabs into per-player replay shards.

    Wraps an already-built plane object (anything with ``recv``/``n_players``)
    and a :class:`~sheeprl_tpu.replay.sharded.ShardedReplay` with
    ``n_shards == plane.n_players``. The learner calls :meth:`ingest` once
    per burst with the received handles; rows go straight from each slab
    view into its owning shard (one copy per shard, no full-width
    concatenation), newest rows are priority-initialized when the sampling
    strategy needs writeback (the Ape-X "insert at max priority" commit
    channel), and handles are released.
    """

    def __init__(self, plane: Any, sharded: Any):
        n_players = int(getattr(plane, "n_players", 1))
        n_shards = int(sharded.n_shards)
        if n_players != n_shards:
            raise ValueError(
                f"replay.shards ({n_shards}) must equal plane.num_players "
                f"({n_players}) so each player process owns exactly one shard"
            )
        self._plane = plane
        self._sharded = sharded
        # ProcessPlane keeps a per-player respawn ledger; LocalPlane (thread
        # mode) has none and never restarts
        self._restarts_seen: Optional[List[int]] = (
            list(getattr(plane, "_restarts"))
            if hasattr(plane, "_restarts")
            else None
        )

    @property
    def plane(self) -> Any:
        return self._plane

    @property
    def sharded(self) -> Any:
        return self._sharded

    @property
    def n_players(self) -> int:
        return int(getattr(self._plane, "n_players", 1))

    def recv(self, update: int) -> List[Any]:
        """One burst's handles, in player order (delegates to the plane)."""
        return [self._plane.recv(p, update) for p in range(self.n_players)]

    def ingest(
        self, handles: Sequence[Any], n_act: int
    ) -> List[Tuple[float, int]]:
        """Land one burst of slab handles into their shards.

        For each player ``p``: arm the staleness clock with that slab's
        commit stamp, add rows ``[:n_act]`` to shard ``p``, initialize the
        newest rows at max priority when the strategy tracks priorities,
        and release the handle. Returns the merged episode stats in player
        order (the same list the single-buffer path assembled)."""
        if len(handles) != self._sharded.n_shards:
            raise ValueError(
                f"got {len(handles)} slab handles for "
                f"{self._sharded.n_shards} shards"
            )
        n_act = int(n_act)
        needs_writeback = self._sharded.needs_writeback
        ep_stats: List[Tuple[float, int]] = []
        for p, h in enumerate(handles):
            commit_ts = float(getattr(h, "commit_ts", 0.0) or 0.0)
            if commit_ts:
                # per-shard stamp — each shard's rows age from their own
                # collection time (recv's burst-level stamp covered only
                # the last handle received)
                _staleness.stamp_next_add(commit_ts)
            rows = {k: v[:n_act] for k, v in h.data.items()}
            self._sharded.add_shard(p, rows)
            if needs_writeback:
                self._sharded.init_priorities_newest(p, n_act)
            ep_stats.extend(h.ep_stats)
            h.release()
        self._observe_restarts()
        return ep_stats

    def _observe_restarts(self) -> None:
        """Fire a ``replay_writer_restart`` flight trigger for any shard
        writer the supervisor respawned since the last ingest."""
        ledger = getattr(self._plane, "_restarts", None)
        if ledger is None or self._restarts_seen is None:
            return
        for p, count in enumerate(ledger):
            if p < len(self._restarts_seen) and count > self._restarts_seen[p]:
                self._restarts_seen[p] = int(count)
                telemetry = get_telemetry()
                if telemetry is not None and telemetry.flight is not None:
                    fills = self._sharded.fills()
                    telemetry.flight.trigger(
                        "replay_writer_restart",
                        {
                            "shard": p,
                            "restart": int(count),
                            "shard_fill": float(fills[p]) if p < len(fills) else 0.0,
                        },
                    )
