"""sheeprl_tpu — a TPU-native reinforcement-learning framework.

A ground-up JAX/XLA re-design with the capability surface of the reference
SheepRL (PyTorch/Lightning-Fabric): the same algorithms, Hydra-style recipes,
replay buffers, and distributed training modes, but built TPU-first — flax
modules, one jit-compiled train step per algorithm with `lax.scan` time loops,
SPMD data-parallelism over a `jax.sharding.Mesh`, numpy host ring buffers
double-buffering host→HBM transfers, and Orbax checkpoints.
"""

from sheeprl_tpu.utils.imports import _IS_WINDOWS  # noqa: F401

__version__ = "0.1.0"

_ALGOS_REGISTERED = False

# Every built-in algorithm module (reference registers them as an import side
# effect in sheeprl/__init__.py:18-45). Modules not present yet simply don't
# register, and the CLI reports what *is* available.
_ALGO_MODULES = [
    "sheeprl_tpu.algos.ppo.ppo",
    "sheeprl_tpu.algos.ppo.ppo_decoupled",
    "sheeprl_tpu.algos.ppo.evaluate",
    "sheeprl_tpu.algos.ppo_recurrent.ppo_recurrent",
    "sheeprl_tpu.algos.ppo_recurrent.evaluate",
    "sheeprl_tpu.algos.a2c.a2c",
    "sheeprl_tpu.algos.a2c.evaluate",
    "sheeprl_tpu.algos.sac.sac",
    "sheeprl_tpu.algos.sac.sac_decoupled",
    "sheeprl_tpu.algos.sac.evaluate",
    "sheeprl_tpu.algos.sac_ae.sac_ae",
    "sheeprl_tpu.algos.sac_ae.evaluate",
    "sheeprl_tpu.algos.droq.droq",
    "sheeprl_tpu.algos.droq.evaluate",
    "sheeprl_tpu.algos.dreamer_v1.dreamer_v1",
    "sheeprl_tpu.algos.dreamer_v1.evaluate",
    "sheeprl_tpu.algos.dreamer_v2.dreamer_v2",
    "sheeprl_tpu.algos.dreamer_v2.evaluate",
    "sheeprl_tpu.algos.dreamer_v3.dreamer_v3",
    "sheeprl_tpu.algos.dreamer_v3.evaluate",
    "sheeprl_tpu.algos.p2e_dv1.p2e_dv1_exploration",
    "sheeprl_tpu.algos.p2e_dv1.p2e_dv1_finetuning",
    "sheeprl_tpu.algos.p2e_dv1.evaluate",
    "sheeprl_tpu.algos.p2e_dv2.p2e_dv2_exploration",
    "sheeprl_tpu.algos.p2e_dv2.p2e_dv2_finetuning",
    "sheeprl_tpu.algos.p2e_dv2.evaluate",
    "sheeprl_tpu.algos.p2e_dv3.p2e_dv3_exploration",
    "sheeprl_tpu.algos.p2e_dv3.p2e_dv3_finetuning",
    "sheeprl_tpu.algos.p2e_dv3.evaluate",
]


def register_algorithms(strict: bool = False) -> None:
    """Import every algorithm module so decorator registration runs.

    Deferred (unlike the reference's eager import block) so that importing
    :mod:`sheeprl_tpu` stays cheap; the CLI calls this before registry lookup.
    """
    global _ALGOS_REGISTERED
    if _ALGOS_REGISTERED:
        return
    import importlib

    for mod in _ALGO_MODULES:
        try:
            importlib.import_module(mod)
        except ModuleNotFoundError as e:
            if strict or not e.name.startswith("sheeprl_tpu"):
                raise
    _ALGOS_REGISTERED = True
