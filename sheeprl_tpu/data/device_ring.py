"""Device-resident replay ring for sequence training.

TPU-native replacement for the reference's host-only replay staging
(``sheeprl/data/buffers.py:528-690`` + per-gradient-step host→device batch
copies): every transition crosses the host→HBM link **once**, when it is
collected, and gradient-step batches are *gathered on device* from a
resident uint8 ring. On a remote-attached chip (or any bandwidth-limited
host link) this turns the train round from transfer-bound into
compute-bound — a [64, 16] pixel batch that costs a 12.6 MB upload per
gradient step becomes an 8 KB index upload.

Design:

- The **host** :class:`~sheeprl_tpu.data.buffers.EnvIndependentReplayBuffer`
  stays the source of truth (checkpointing, fault-tolerance patches); this
  class wraps it and mirrors every ``add`` into a device ring of the same
  per-env geometry.
- **Index planning stays on the host and reuses the host buffers' own
  logic** (:meth:`SequentialReplayBuffer.plan_starts`,
  :meth:`EnvIndependentReplayBuffer.pick_envs` semantics), so sampling
  semantics can never diverge between the two paths; only the final
  *gather* runs on device.
- Writes are **staged and flushed lazily** (one scatter per training burst,
  padded to shape buckets so XLA compiles a handful of programs); padding
  rows carry out-of-bounds targets and are dropped by the scatter
  (``mode="drop"``).
- **Multi-chip**: pass ``batch_sharding`` (the train burst's
  ``NamedSharding``, batch axis sharded over the mesh ``data`` axis) and the
  ring shards itself over the mesh: envs are split into one contiguous group
  per data-axis device — group *g* homed on exactly the device that consumes
  batch slice *g* (derived from the sharding's index map) — and every device
  owns a private ring shard with device-local scatter/gather jits.
  ``sample_device`` plans each device's batch columns among its *local* envs
  (uniform within the group, like the host's ``pick_envs`` is uniform
  globally) and assembles the global ``[n, L, B, ...]`` batch with
  :func:`jax.make_array_from_single_device_arrays` — transitions cross the
  host link once to their home device, gathers are local DMA, and the
  assembled batch needs **no resharding collective** inside the train step.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, _as_np
from sheeprl_tpu.obs.counters import staged_device_put

__all__ = ["DeviceRingReplay"]


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def _batch_shard_count(batch_sharding) -> int:
    """Distinct shards along the batch axis (dim 2) of the burst sharding.

    The ring expects the burst layout ``[n_samples, seq, batch, ...]`` with
    only dim 2 sharded (e.g. ``P(None, None, 'data')``). A spec that shards
    some other dim — say a caller passed ``P('data')`` meant for a different
    layout — would quietly build one shard here and then blow up deep inside
    ``make_array_from_single_device_arrays`` at sample time, far from the
    mistake, so validate eagerly.
    """
    spec = tuple(batch_sharding.spec)
    for dim, entry in enumerate(spec):
        if dim != 2 and entry is not None:
            raise ValueError(
                "DeviceRingReplay batch_sharding must shard only the batch "
                f"axis (dim 2) of the [n_samples, seq, batch, ...] burst; got "
                f"PartitionSpec{spec} which shards dim {dim}. Pass e.g. "
                "NamedSharding(mesh, P(None, None, 'data'))."
            )
    entry = spec[2] if len(spec) > 2 else None
    if entry is None:
        return 1
    axes = (entry,) if isinstance(entry, str) else tuple(entry)
    size = 1
    for a in axes:
        size *= int(batch_sharding.mesh.shape[a])
    return size


class DeviceRingReplay:
    """Wrap an :class:`EnvIndependentReplayBuffer` with a device-side mirror.

    ``add`` forwards to the host buffer and stages the same rows for the
    device ring; ``sample_device`` returns a dict of **device** arrays shaped
    ``[n_samples, sequence_length, batch, ...]`` (the same layout as the host
    ``sample``), produced by an on-device gather. With ``batch_sharding`` the
    arrays are global jax Arrays sharded batch-wise over the mesh.
    """

    #: flush scatters are padded to multiples of this many rows so repeated
    #: bursts reuse a few compiled programs instead of one per row count
    FLUSH_BUCKET = 32

    def __init__(
        self,
        host_rb: EnvIndependentReplayBuffer,
        device: Optional[Any] = None,
        seed: Optional[int] = None,
        sequence_overlap: int = 64,
        batch_sharding: Optional[Any] = None,
    ):
        import jax

        self._rb = host_rb
        self._capacity = int(host_rb.buffer_size)
        self._n_envs = int(host_rb.n_envs)
        # Shadow region: the first `overlap` rows are mirrored past the tail
        # so every sequence of length ≤ overlap is PHYSICALLY contiguous even
        # when it wraps, and sampling can read contiguous blocks (vmapped
        # dynamic_slice) instead of row-scattered gathers — on TPU a gather
        # of thousands of random 12 KB rows from a GB-scale ring is ~100x
        # slower than the same bytes as contiguous block DMA (measured:
        # ~0.5 s/sample at 100k rows vs ~ms for blocks).
        self._overlap = max(0, min(int(sequence_overlap), self._capacity))
        self._rng = np.random.default_rng(seed)
        self._sharding = batch_sharding

        if batch_sharding is not None:
            n_groups = _batch_shard_count(batch_sharding)
            if self._n_envs < n_groups or self._n_envs % n_groups != 0:
                # uneven groups would silently oversample the smaller groups'
                # envs relative to the host path's global-uniform pick_envs
                raise ValueError(
                    f"DeviceRingReplay needs the same number of envs on every "
                    f"batch shard: n_envs={self._n_envs} does not divide over "
                    f"{n_groups} data-axis shards"
                )
            # device that OWNS batch slice g (plus any replicas along other
            # mesh axes): probe the index map with a [1, 1, n_groups] shape —
            # slice starts enumerate the shard order along the batch dim
            probe = batch_sharding.addressable_devices_indices_map((1, 1, n_groups))
            by_slice: Dict[int, List[Any]] = {}
            for dev, idx in probe.items():
                start = idx[2].start or 0
                by_slice.setdefault(int(start), []).append(dev)
            if sorted(by_slice) != list(range(n_groups)):
                raise ValueError(
                    "DeviceRingReplay: batch sharding is not addressable shard-"
                    "per-slice from this process (multi-host meshes must pass "
                    "a process-local batch sharding)"
                )
            self._homes = [sorted(by_slice[g], key=lambda d: d.id)[0] for g in range(n_groups)]
            self._replicas = [
                [d for d in sorted(by_slice[g], key=lambda d: d.id) if d is not self._homes[g]]
                for g in range(n_groups)
            ]
        else:
            self._homes = [device if device is not None else jax.devices()[0]]
            self._replicas = [[]]

        n_groups = len(self._homes)
        self._groups: List[np.ndarray] = [
            np.asarray(g, np.int64) for g in np.array_split(np.arange(self._n_envs), n_groups)
        ]
        self._env_group = np.empty(self._n_envs, np.int64)
        self._env_col = np.empty(self._n_envs, np.int64)
        for g, envs in enumerate(self._groups):
            self._env_group[envs] = g
            self._env_col[envs] = np.arange(len(envs))

        # per-group device storage, allocated lazily on the first add
        # (dtypes/shapes are discovered from the data, like the host buffer)
        self._shards: Optional[List[Dict[str, Any]]] = None
        # staged (env, target_index) slots; row *values* are read back from
        # the host buffer at flush time (it owns the newest copy of every
        # slot, so no per-step duplicate row copies are held here)
        self._staged: List[Tuple[int, int]] = []
        self._scatter_fns: Dict[int, Any] = {}
        self._gather_fns: Dict[Tuple[int, int, int], Any] = {}

    # -- proxied host surface ---------------------------------------------

    @property
    def host(self) -> EnvIndependentReplayBuffer:
        return self._rb

    @property
    def buffer(self):
        return self._rb.buffer

    @property
    def buffer_size(self) -> int:
        return self._rb.buffer_size

    @property
    def n_envs(self) -> int:
        return self._rb.n_envs

    @property
    def _device(self):
        return self._homes[0]

    @property
    def _buf(self) -> Optional[Dict[str, Any]]:
        """Single-shard view (tests / single-device introspection)."""
        if self._shards is None:
            return None
        if len(self._shards) != 1:
            raise AttributeError("_buf is only defined for single-shard rings")
        return self._shards[0]

    def seed(self, seed: Optional[int] = None) -> None:
        self._rb.seed(seed)
        self._rng = np.random.default_rng(seed)

    def state_dict(self) -> Dict[str, Any]:
        return self._rb.state_dict()

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        """Restore the host buffer, then re-mirror its filled region to the
        device shards as one contiguous block upload per key per shard."""
        import jax

        self._rb.load_state_dict(state)
        self._shards = None
        self._staged.clear()
        n_rows = np.zeros(self._n_envs, np.int64)
        example: Optional[Dict[str, np.ndarray]] = None
        for env, sub in enumerate(self._rb.buffer):
            if sub._buf is None:
                continue
            n_rows[env] = sub.buffer_size if sub.full else sub._pos
            if example is None:
                example = {k: _as_np(v)[0, 0] for k, v in sub._buf.items()}
        if example is None or int(n_rows.max()) == 0:
            return
        self._allocate(example)
        cap, ov = self._capacity, self._overlap

        def _set(v, b):
            v = v.at[: b.shape[0]].set(b)
            if ov:
                # mirror the head into the shadow region
                v = v.at[cap:].set(v[:ov])
            return v

        set_block = jax.jit(
            lambda buf, blk: {k: _set(v, blk[k]) for k, v in buf.items()},
            donate_argnums=(0,),
        )
        for g, envs in enumerate(self._groups):
            max_rows = int(n_rows[envs].max()) if len(envs) else 0
            if max_rows == 0:
                continue
            blocks: Dict[str, np.ndarray] = {}
            for k, v0 in example.items():
                block = np.zeros(
                    (max_rows, len(envs)) + np.asarray(v0).shape, np.asarray(v0).dtype
                )
                for col, env in enumerate(envs):
                    sub = self._rb.buffer[env]
                    if sub._buf is not None and n_rows[env] > 0:
                        block[: n_rows[env], col] = _as_np(sub._buf[k])[: n_rows[env], 0]
                blocks[k] = block
            blocks = staged_device_put(blocks, self._homes[g])
            self._shards[g] = set_block(self._shards[g], blocks)

    # -- write path --------------------------------------------------------

    def add(
        self,
        data: Dict[str, np.ndarray],
        env_idxes: Optional[Sequence[int]] = None,
        validate_args: bool = False,
    ) -> None:
        if env_idxes is None:
            env_idxes = list(range(self._n_envs))
        # capture write targets before the host add advances them (and let a
        # failing host add leave the mirror untouched)
        targets = [int(self._rb.buffer[env]._pos) for env in env_idxes]
        self._rb.add(data, env_idxes, validate_args=validate_args)
        rows = next(iter(data.values())).shape[0]
        for col, env in enumerate(env_idxes):
            for r in range(rows):
                self._staged.append((env, (targets[col] + r) % self._capacity))
        # bound host-side staging memory (and batch the upload) during long
        # collection-only phases such as the learning_starts prefill
        if len(self._staged) >= 8 * self.FLUSH_BUCKET:
            self._flush()

    def force_done_last(self, env: int) -> None:
        """Fault-tolerance patch (reference dreamer_v3.py:642-650): mark the
        most recent stored step of ``env`` as terminal on both copies."""
        sub = self._rb.buffer[env]
        last_idx = (sub._pos - 1) % sub.buffer_size
        sub["dones"][last_idx] = np.ones_like(sub["dones"][last_idx])
        sub["is_first"][last_idx] = np.zeros_like(sub["is_first"][last_idx])
        self._staged.append((env, int(last_idx)))

    # -- device plumbing ---------------------------------------------------

    def _allocate(self, example_row: Dict[str, np.ndarray]) -> None:
        import warnings

        import jax
        import jax.numpy as jnp

        # every shard is (capacity + overlap) x group_envs of EVERY key in
        # HBM; with DV3's default buffer.size=1e6 of 64x64x3 uint8 pixels the
        # whole ring is ~12 GB before model/optimizer state. Fail with the
        # computed size (and the size that fits) instead of an opaque XLA
        # allocation error later.
        rows = self._capacity + self._overlap
        max_group = max(len(g) for g in self._groups)
        bytes_per_row = sum(
            int(np.prod(np.asarray(v).shape)) * np.asarray(v).dtype.itemsize * max_group
            for v in example_row.values()
        )
        total = rows * bytes_per_row  # largest single-device shard
        from sheeprl_tpu.obs.counters import device_memory_stats

        stats = device_memory_stats(self._homes[0])
        limit = stats.get("bytes_limit") if stats else None
        if limit and total > 0.95 * limit:
            # certain OOM: the ring alone leaves no room for params/optimizer
            fit_rows = max(int(0.5 * limit / max(bytes_per_row, 1)) - self._overlap, 0)
            raise ValueError(
                f"DeviceRingReplay would allocate {total / 2**30:.2f} GiB "
                f"({rows} rows x {bytes_per_row} B) on a device with a "
                f"{limit / 2**30:.2f} GiB limit; a ring of <= {fit_rows} per-env "
                f"rows fits in half the device (buffer.size <= "
                f"{fit_rows * self._n_envs} under the buffer.size//n_envs "
                "convention), or disable buffer.device_ring"
            )
        if (limit and total > 0.6 * limit) or total > 4 * 2**30:
            warnings.warn(
                f"DeviceRingReplay allocating {total / 2**30:.2f} GiB of HBM "
                f"per device ({rows} per-env rows x {bytes_per_row} B"
                + (f", device limit {limit / 2**30:.2f} GiB" if limit else "")
                + "); lower buffer.size if the device OOMs",
                UserWarning,
            )
        self._shards = []
        for g, envs in enumerate(self._groups):
            with jax.default_device(self._homes[g]):
                self._shards.append(
                    {
                        k: jnp.zeros(
                            (rows, len(envs)) + np.asarray(v).shape, np.asarray(v).dtype
                        )
                        for k, v in example_row.items()
                    }
                )

    def _scatter_fn(self, n_rows: int):
        import jax

        fn = self._scatter_fns.get(n_rows)
        if fn is None:
            def scatter(buf, t_idx, e_idx, rows):
                return {
                    k: v.at[t_idx, e_idx].set(rows[k], mode="drop")
                    for k, v in buf.items()
                }

            fn = jax.jit(scatter, donate_argnums=(0,))
            self._scatter_fns[n_rows] = fn
        return fn

    def _flush(self) -> None:
        import jax

        if not self._staged:
            return
        # dedupe (env, t) slots: XLA's scatter leaves the winner among
        # duplicate indices undefined, and duplicates are legal here
        # (force_done_last re-stages the slot its add() just wrote; a ring
        # can wrap within one staging window). Values are read from the host
        # buffer, which always holds the newest write for a slot.
        slots = list(dict.fromkeys(self._staged))
        sub0 = self._rb.buffer[slots[0][0]]
        if self._shards is None:
            self._allocate({k: _as_np(v)[0, 0] for k, v in sub0._buf.items()})
        # head rows are mirrored into the shadow region past the tail so
        # wrapped sequences stay physically contiguous (value read from the
        # same host slot)
        slots.extend([(env, t + self._capacity) for env, t in slots if t < self._overlap])
        slots_arr = np.asarray(slots, np.int64).reshape(len(slots), 2)
        envs, ts = slots_arr[:, 0], slots_arr[:, 1] % self._capacity
        oob = self._capacity + self._overlap
        for g in range(len(self._groups)):
            sel = np.nonzero(self._env_group[envs] == g)[0]
            if sel.size == 0:
                continue
            n = int(sel.size)
            padded = _round_up(n, self.FLUSH_BUCKET)
            t_idx = np.full(padded, oob, np.int32)  # OOB → dropped
            e_idx = np.zeros(padded, np.int32)
            t_idx[:n] = slots_arr[sel, 1]
            e_idx[:n] = self._env_col[envs[sel]]
            # group slots by env and gather each env's rows with one
            # fancy-index read (a per-row Python loop was thousands of small
            # copies per flush on a 1-core host, inside the acting timer);
            # the (src rows, dst positions) maps depend only on the env split
            by_env = {}
            for env in np.unique(envs[sel]):
                pos = sel[np.nonzero(envs[sel] == env)[0]]
                by_env[int(env)] = (pos, np.searchsorted(sel, pos))
            rows: Dict[str, np.ndarray] = {}
            for k, v0 in sub0._buf.items():
                first = _as_np(v0)[0, 0]
                stack = np.zeros((padded,) + first.shape, first.dtype)
                for env, (pos, dst) in by_env.items():
                    stack[dst] = _as_np(self._rb.buffer[env]._buf[k])[ts[pos], 0]
                rows[k] = stack
            payload = staged_device_put((t_idx, e_idx, rows), self._homes[g])
            self._shards[g] = self._scatter_fn(padded)(self._shards[g], *payload)
        self._staged.clear()

    # -- sample path -------------------------------------------------------

    def _plan_group(
        self, envs: np.ndarray, batch: int, sequence_length: int, n_samples: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Host-side index plan for one group, reusing the host buffers' own
        sampling logic (``pick_envs`` restricted to the group's envs + per-env
        ``plan_starts``).

        Returns ``(starts [n_samples * batch], cols [n_samples * batch])``
        ordered sample-major with per-env column groups, matching the host
        ``EnvIndependentReplayBuffer.sample`` concat layout. Starts are
        physical ring rows; a sequence always occupies the ``L`` contiguous
        rows from its start thanks to the shadow region.
        """
        L = sequence_length
        try:
            with_data, counts = self._rb.pick_envs(batch, self._rng, envs=[int(e) for e in envs])
        except ValueError as exc:
            # Intended behavior, made diagnosable: each device can only gather
            # from its LOCAL ring shard, so an empty group cannot borrow
            # another group's envs (the host path would sample globally; here
            # that would require a cross-device read that defeats the ring's
            # no-collective design). Groups fill in lockstep during normal
            # collection — this only triggers when e.g. a checkpoint taken
            # before every env had collected is restored under sharding.
            raise ValueError(
                f"Device-ring group {sorted(int(e) for e in envs)} has no "
                "samples while sampling was requested. Sharded rings sample "
                "per-group by design (device-local gathers); collect at least "
                "one sequence on every env group before sampling, or restore "
                "a checkpoint whose buffer covers all env groups."
            ) from exc
        starts_by_env: List[np.ndarray] = []
        envs_order: List[int] = []
        for j, env in enumerate(with_data):
            c = int(counts[j])
            if c == 0:
                continue
            starts = self._rb.buffer[env].plan_starts(c * n_samples, L, rng=self._rng)
            starts_by_env.append(np.asarray(starts).reshape(n_samples, c))
            envs_order.append(env)
        all_starts = np.concatenate(starts_by_env, axis=1)  # [n_samples, B]
        all_cols = np.concatenate(
            [
                np.full((n_samples, s.shape[1]), self._env_col[e], np.int32)
                for s, e in zip(starts_by_env, envs_order)
            ],
            axis=1,
        )
        return all_starts.reshape(-1).astype(np.int32), all_cols.reshape(-1).astype(np.int32)

    def _gather_fn(self, n_rows: int, L: int, n_samples: int):
        import jax

        key = (n_rows, L, n_samples)
        fn = self._gather_fns.get(key)
        if fn is None:
            def gather(buf, starts, e_idx):
                # contiguous-block reads (thanks to the shadow region): a
                # vmapped dynamic_slice lowers to a gather of [L, ...] BLOCKS,
                # not L scattered rows — the difference between ~ms and
                # ~hundreds of ms per sample on a GB-scale TPU ring
                def one(s, e):
                    return {
                        k: jax.lax.dynamic_slice(
                            v, (s, e) + (0,) * (v.ndim - 2), (L, 1) + v.shape[2:]
                        )[:, 0]
                        for k, v in buf.items()
                    }

                sel = jax.vmap(one)(starts, e_idx)  # {k: [total, L, ...]}
                out = {}
                for k, v in sel.items():
                    v = v.reshape((n_samples, n_rows // n_samples, L) + v.shape[2:])
                    out[k] = v.swapaxes(1, 2)  # [n_samples, L, B, ...]
                return out

            fn = jax.jit(gather)
            self._gather_fns[key] = fn
        return fn

    def sample_device(
        self, batch_size: int, sequence_length: int = 1, n_samples: int = 1
    ) -> Dict[str, Any]:
        """Gather ``[n_samples, sequence_length, batch, ...]`` batches on
        device. The only host→device traffic is the int32 index plan. With a
        ``batch_sharding`` the result is a global sharded Array whose batch
        slice *g* was gathered (and stays) on the device that consumes it."""
        import jax

        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        if sequence_length <= 0:
            raise ValueError(f"'sequence_length' ({sequence_length}) must be greater than 0")
        if sequence_length > max(self._overlap, 1) and any(
            b.full for b in self._rb.buffer
        ):
            raise ValueError(
                f"sequence_length {sequence_length} exceeds the ring's "
                f"sequence_overlap {self._overlap}; construct DeviceRingReplay "
                "with sequence_overlap >= the training sequence length"
            )
        n_groups = len(self._groups)
        if batch_size % n_groups != 0:
            raise ValueError(
                f"batch_size {batch_size} must divide evenly over the "
                f"{n_groups} batch shards"
            )
        self._flush()
        if self._shards is None:
            raise ValueError("No sample has been added to the buffer")
        b_local = batch_size // n_groups
        parts: List[Dict[str, Any]] = []
        for g, envs in enumerate(self._groups):
            starts, cols = self._plan_group(envs, b_local, sequence_length, n_samples)
            fn = self._gather_fn(starts.shape[0], sequence_length, n_samples)
            # the index plan is the ONLY host→device traffic of a ring sample;
            # counting it keeps the telemetry's bytes_staged_h2d an honest
            # total (and shows how little the ring ships vs host staging)
            starts, cols = staged_device_put((starts, cols), self._homes[g])
            parts.append(fn(self._shards[g], starts, cols))
        if self._sharding is None:
            return parts[0]
        # assemble the global batch: shard g is already resident on its home
        # device; replicas along non-data mesh axes (if any) receive a copy
        out: Dict[str, Any] = {}
        for k in parts[0]:
            shape = parts[0][k].shape
            global_shape = (shape[0], shape[1], batch_size) + shape[3:]
            arrays = []
            for g in range(n_groups):
                arrays.append(parts[g][k])
                for dev in self._replicas[g]:
                    arrays.append(jax.device_put(parts[g][k], dev))
            out[k] = jax.make_array_from_single_device_arrays(
                global_shape, self._sharding, arrays
            )
        return out
