"""Device-resident replay ring for sequence training.

TPU-native replacement for the reference's host-only replay staging
(``sheeprl/data/buffers.py:528-690`` + per-gradient-step host→device batch
copies): every transition crosses the host→HBM link **once**, when it is
collected, and gradient-step batches are *gathered on device* from a
resident uint8 ring. On a remote-attached chip (or any bandwidth-limited
host link) this turns the train round from transfer-bound into
compute-bound — a [64, 16] pixel batch that costs a 12.6 MB upload per
gradient step becomes an 8 KB index upload.

Design:

- The **host** :class:`~sheeprl_tpu.data.buffers.EnvIndependentReplayBuffer`
  stays the source of truth (checkpointing, fault-tolerance patches); this
  class wraps it and mirrors every ``add`` into a device ring of the same
  per-env geometry.
- **Index planning stays on the host and reuses the host buffers' own
  logic** (:meth:`SequentialReplayBuffer.plan_starts`,
  :meth:`EnvIndependentReplayBuffer.pick_envs` semantics), so sampling
  semantics can never diverge between the two paths; only the final
  *gather* runs on device.
- Writes are **staged and flushed lazily** (one scatter per training burst,
  padded to shape buckets so XLA compiles a handful of programs); padding
  rows carry out-of-bounds targets and are dropped by the scatter
  (``mode="drop"``).
- **Multi-chip**: pass ``batch_sharding`` (the train burst's
  ``NamedSharding``, batch axis sharded over the mesh ``data`` axis) and the
  ring shards itself over the mesh: envs are split into one contiguous group
  per data-axis device — group *g* homed on exactly the device that consumes
  batch slice *g* (derived from the sharding's index map) — and every device
  owns a private ring shard with device-local scatter/gather jits.
  ``sample_device`` plans each device's batch columns among its *local* envs
  (uniform within the group, like the host's ``pick_envs`` is uniform
  globally) and assembles the global ``[n, L, B, ...]`` batch with
  :func:`jax.make_array_from_single_device_arrays` — transitions cross the
  host link once to their home device, gathers are local DMA, and the
  assembled batch needs **no resharding collective** inside the train step.
"""

from __future__ import annotations

from contextlib import nullcontext
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, ReplayBuffer, _as_np
from sheeprl_tpu.obs.counters import add_replay_adoption, staged_device_put

__all__ = ["DeviceRingReplay", "DeviceRingTransitions", "scatter_append"]


def scatter_append(bufs: Dict[str, Any], pos: Any, rows: Dict[str, Any], capacity: int) -> Dict[str, Any]:
    """In-jit ring append: write ``rows`` (leaves ``[T, n_envs, ...]``) at
    time slots ``(pos + t) % capacity`` of ``bufs`` (leaves ``[capacity,
    n_envs, ...]``) and return the updated buffers.

    This is the write half of the jitted-scan collection path
    (:mod:`sheeprl_tpu.envs.rollout.engine`): traceable, so an entire
    collection burst — act, env step, ring add — stays inside one XLA
    program with zero host involvement. ``pos`` may be a traced int32
    scalar; ``capacity`` must be static. ``T`` (static, from the row
    shapes) must not exceed ``capacity``: a longer burst would land
    duplicate slot indices in one scatter, whose winner XLA leaves
    undefined (the host ``add`` keeps only the trailing window in that
    case — split the burst instead).
    """
    import jax.numpy as jnp

    first = next(iter(rows.values()))
    t = int(first.shape[0])
    if t > capacity:
        raise ValueError(
            f"scatter_append burst of {t} rows exceeds the ring capacity "
            f"{capacity}; split the burst (duplicate slots in one scatter "
            "are undefined)"
        )
    t_idx = (pos + jnp.arange(t, dtype=jnp.int32)) % capacity
    return {k: v.at[t_idx].set(rows[k]) for k, v in bufs.items()}


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def _pad_rows(n: int) -> int:
    """Flush-scatter padding: next power of two.

    A fixed 32-row bucket compiled few programs but uploaded up to 32x the
    staged bytes in the steady state (one collected row per training burst,
    padded to a full bucket, every burst); power-of-two buckets bound the
    padding waste at <2x while still reusing ~log2(max flush) compiled
    scatter programs."""
    return 1 << max(n - 1, 0).bit_length()


def _batch_shard_count(batch_sharding, batch_dim: int = 2, layout: str = "[n_samples, seq, batch, ...]") -> int:
    """Distinct shards along the batch axis (``batch_dim``) of the sharding.

    The ring expects only the batch dim sharded (e.g. ``P(None, None, 'data')``
    for the sequence burst, ``P(None, 'data')`` for the transition burst). A
    spec that shards some other dim — say a caller passed ``P('data')`` meant
    for a different layout — would quietly build one shard here and then blow
    up deep inside ``make_array_from_single_device_arrays`` at sample time,
    far from the mistake, so validate eagerly.
    """
    spec = tuple(batch_sharding.spec)
    for dim, entry in enumerate(spec):
        if dim != batch_dim and entry is not None:
            raise ValueError(
                "Device-ring batch_sharding must shard only the batch "
                f"axis (dim {batch_dim}) of the {layout} burst; got "
                f"PartitionSpec{spec} which shards dim {dim}."
            )
    entry = spec[batch_dim] if len(spec) > batch_dim else None
    if entry is None:
        return 1
    axes = (entry,) if isinstance(entry, str) else tuple(entry)
    size = 1
    for a in axes:
        size *= int(batch_sharding.mesh.shape[a])
    return size


def _homes_for_sharding(batch_sharding, batch_dim: int, n_groups: int) -> Tuple[List[Any], List[List[Any]]]:
    """Device that OWNS batch slice g (plus replicas along other mesh axes):
    probe the index map with a shape of ``n_groups`` along the batch dim —
    slice starts enumerate the shard order along that dim."""
    probe_shape = tuple(n_groups if d == batch_dim else 1 for d in range(batch_dim + 1))
    probe = batch_sharding.addressable_devices_indices_map(probe_shape)
    by_slice: Dict[int, List[Any]] = {}
    for dev, idx in probe.items():
        start = idx[batch_dim].start or 0
        by_slice.setdefault(int(start), []).append(dev)
    if sorted(by_slice) != list(range(n_groups)):
        raise ValueError(
            "Device ring: batch sharding is not addressable shard-per-slice "
            "from this process (multi-host meshes must pass a process-local "
            "batch sharding)"
        )
    homes = [sorted(by_slice[g], key=lambda d: d.id)[0] for g in range(n_groups)]
    replicas = [
        [d for d in sorted(by_slice[g], key=lambda d: d.id) if d is not homes[g]]
        for g in range(n_groups)
    ]
    return homes, replicas


def _check_hbm_budget(device, rows: int, bytes_per_row: int, kind: str, fit_rows, n_envs: int) -> None:
    """Pre-allocation HBM guard shared by both rings: ``rows x bytes_per_row``
    is the largest single-device shard. Fail with the computed size (and the
    ``buffer.size`` that would fit in half the device, via ``fit_rows(limit)``)
    instead of an opaque XLA allocation error later; warn when the ring would
    crowd the device. With DV3's default buffer.size=1e6 of 64x64x3 uint8
    pixels the whole ring is ~12 GB before model/optimizer state."""
    import warnings

    from sheeprl_tpu.obs.counters import device_memory_stats

    total = rows * bytes_per_row
    stats = device_memory_stats(device)
    limit = stats.get("bytes_limit") if stats else None
    if limit and total > 0.95 * limit:
        # certain OOM: the ring alone leaves no room for params/optimizer
        rows_fit = max(int(fit_rows(limit)), 0)
        raise ValueError(
            f"{kind} would allocate {total / 2**30:.2f} GiB "
            f"({rows} rows x {bytes_per_row} B) on a device with a "
            f"{limit / 2**30:.2f} GiB limit; a ring of <= {rows_fit} per-env "
            f"rows fits in half the device (buffer.size <= "
            f"{rows_fit * n_envs} under the buffer.size//n_envs "
            "convention), or disable buffer.device_ring"
        )
    if (limit and total > 0.6 * limit) or total > 4 * 2**30:
        warnings.warn(
            f"{kind} allocating {total / 2**30:.2f} GiB of HBM "
            f"per device ({rows} per-env rows x {bytes_per_row} B"
            + (f", device limit {limit / 2**30:.2f} GiB" if limit else "")
            + "); lower buffer.size if the device OOMs",
            UserWarning,
        )


def _assemble_global(parts: List[Dict[str, Any]], sharding, replicas, batch_dim: int, batch_size: int) -> Dict[str, Any]:
    """Assemble per-group device gathers into global sharded Arrays: shard
    *g* is already resident on its home device; replicas along non-batch
    mesh axes (if any) receive a copy. No resharding collective."""
    import jax

    out: Dict[str, Any] = {}
    for k in parts[0]:
        shape = parts[0][k].shape
        global_shape = shape[:batch_dim] + (batch_size,) + shape[batch_dim + 1 :]
        arrays = []
        for g, part in enumerate(parts):
            arrays.append(part[k])
            for dev in replicas[g]:
                arrays.append(jax.device_put(part[k], dev))
        out[k] = jax.make_array_from_single_device_arrays(global_shape, sharding, arrays)
    return out


class DeviceRingReplay:
    """Wrap an :class:`EnvIndependentReplayBuffer` with a device-side mirror.

    ``add`` forwards to the host buffer and stages the same rows for the
    device ring; ``sample_device`` returns a dict of **device** arrays shaped
    ``[n_samples, sequence_length, batch, ...]`` (the same layout as the host
    ``sample``), produced by an on-device gather. With ``batch_sharding`` the
    arrays are global jax Arrays sharded batch-wise over the mesh.
    """

    #: host-side staging threshold: a flush is forced once 8x this many
    #: rows are staged (bounds staging memory during collection-only phases);
    #: the scatter itself pads to power-of-two buckets (_pad_rows)
    FLUSH_BUCKET = 32

    def __init__(
        self,
        host_rb: EnvIndependentReplayBuffer,
        device: Optional[Any] = None,
        seed: Optional[int] = None,
        sequence_overlap: int = 64,
        batch_sharding: Optional[Any] = None,
    ):
        import jax

        self._rb = host_rb
        self._capacity = int(host_rb.buffer_size)
        self._n_envs = int(host_rb.n_envs)
        # Shadow region: the first `overlap` rows are mirrored past the tail
        # so every sequence of length ≤ overlap is PHYSICALLY contiguous even
        # when it wraps, and sampling can read contiguous blocks (vmapped
        # dynamic_slice) instead of row-scattered gathers — on TPU a gather
        # of thousands of random 12 KB rows from a GB-scale ring is ~100x
        # slower than the same bytes as contiguous block DMA (measured:
        # ~0.5 s/sample at 100k rows vs ~ms for blocks).
        self._overlap = max(0, min(int(sequence_overlap), self._capacity))
        self._rng = np.random.default_rng(seed)
        self._sharding = batch_sharding

        if batch_sharding is not None:
            n_groups = _batch_shard_count(batch_sharding)
            if self._n_envs < n_groups or self._n_envs % n_groups != 0:
                # uneven groups would silently oversample the smaller groups'
                # envs relative to the host path's global-uniform pick_envs
                raise ValueError(
                    f"DeviceRingReplay needs the same number of envs on every "
                    f"batch shard: n_envs={self._n_envs} does not divide over "
                    f"{n_groups} data-axis shards"
                )
            self._homes, self._replicas = _homes_for_sharding(batch_sharding, 2, n_groups)
        else:
            self._homes = [device if device is not None else jax.devices()[0]]
            self._replicas = [[]]

        n_groups = len(self._homes)
        self._groups: List[np.ndarray] = [
            np.asarray(g, np.int64) for g in np.array_split(np.arange(self._n_envs), n_groups)
        ]
        self._env_group = np.empty(self._n_envs, np.int64)
        self._env_col = np.empty(self._n_envs, np.int64)
        for g, envs in enumerate(self._groups):
            self._env_group[envs] = g
            self._env_col[envs] = np.arange(len(envs))

        # per-group device storage, allocated lazily on the first add
        # (dtypes/shapes are discovered from the data, like the host buffer)
        self._shards: Optional[List[Dict[str, Any]]] = None
        # staged (env, target_index) slots; row *values* are read back from
        # the host buffer at flush time (it owns the newest copy of every
        # slot, so no per-step duplicate row copies are held here)
        self._staged: List[Tuple[int, int]] = []
        self._scatter_fns: Dict[int, Any] = {}
        self._gather_fns: Dict[Tuple[int, int, int], Any] = {}
        self._write_lock: Optional[Any] = None
        # wrapping a buffer that already holds data (e.g. restored from a
        # checkpoint before the ring was constructed): mirror it now instead
        # of depending on wrap-then-load call order
        if any(not sub.empty for sub in host_rb.buffer):
            self._remirror_from_host()

    # -- proxied host surface ---------------------------------------------

    @property
    def host(self) -> EnvIndependentReplayBuffer:
        return self._rb

    @property
    def buffer(self):
        return self._rb.buffer

    @property
    def buffer_size(self) -> int:
        return self._rb.buffer_size

    @property
    def n_envs(self) -> int:
        return self._rb.n_envs

    @property
    def _device(self):
        return self._homes[0]

    @property
    def _buf(self) -> Optional[Dict[str, Any]]:
        """Single-shard view (tests / single-device introspection)."""
        if self._shards is None:
            return None
        if len(self._shards) != 1:
            raise AttributeError("_buf is only defined for single-shard rings")
        return self._shards[0]

    def seed(self, seed: Optional[int] = None) -> None:
        self._rb.seed(seed)
        self._rng = np.random.default_rng(seed)

    def bind_write_lock(self, lock: Any) -> None:
        """Serialize ``add``/``force_done_last`` against a concurrent
        ``sample_device`` (decoupled player/trainer threads): the staged-slot
        list and the host mirror are shared mutable state."""
        self._write_lock = lock

    def state_dict(self) -> Dict[str, Any]:
        return self._rb.state_dict()

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        """Restore the host buffer, then re-mirror its filled region to the
        device shards as one contiguous block upload per key per shard."""
        self._rb.load_state_dict(state)
        self._remirror_from_host()

    def _remirror_from_host(self) -> None:
        """Rebuild the device shards from whatever the host buffer holds —
        after a checkpoint restore, or at construction when wrapping a buffer
        that was filled/restored before the ring existed."""
        import jax

        self._shards = None
        self._staged.clear()
        n_rows = np.zeros(self._n_envs, np.int64)
        example: Optional[Dict[str, np.ndarray]] = None
        for env, sub in enumerate(self._rb.buffer):
            if sub._buf is None:
                continue
            n_rows[env] = sub.buffer_size if sub.full else sub._pos
            if example is None:
                example = {k: _as_np(v)[0, 0] for k, v in sub._buf.items()}
        if example is None or int(n_rows.max()) == 0:
            return
        self._allocate(example)
        cap, ov = self._capacity, self._overlap

        def _set(v, b):
            v = v.at[: b.shape[0]].set(b)
            if ov:
                # mirror the head into the shadow region
                v = v.at[cap:].set(v[:ov])
            return v

        set_block = jax.jit(
            lambda buf, blk: {k: _set(v, blk[k]) for k, v in buf.items()},
            donate_argnums=(0,),
        )
        for g, envs in enumerate(self._groups):
            max_rows = int(n_rows[envs].max()) if len(envs) else 0
            if max_rows == 0:
                continue
            blocks: Dict[str, np.ndarray] = {}
            for k, v0 in example.items():
                block = np.zeros(
                    (max_rows, len(envs)) + np.asarray(v0).shape, np.asarray(v0).dtype
                )
                for col, env in enumerate(envs):
                    sub = self._rb.buffer[env]
                    if sub._buf is not None and n_rows[env] > 0:
                        block[: n_rows[env], col] = _as_np(sub._buf[k])[: n_rows[env], 0]
                blocks[k] = block
            blocks = staged_device_put(blocks, self._homes[g])
            self._shards[g] = set_block(self._shards[g], blocks)

    # -- write path --------------------------------------------------------

    def add(
        self,
        data: Dict[str, np.ndarray],
        env_idxes: Optional[Sequence[int]] = None,
        validate_args: bool = False,
    ) -> None:
        if env_idxes is None:
            env_idxes = list(range(self._n_envs))
        with self._write_lock or nullcontext():
            # capture write targets before the host add advances them (and let
            # a failing host add leave the mirror untouched)
            targets = [int(self._rb.buffer[env]._pos) for env in env_idxes]
            self._rb.add(data, env_idxes, validate_args=validate_args)
            rows = next(iter(data.values())).shape[0]
            for col, env in enumerate(env_idxes):
                for r in range(rows):
                    self._staged.append((env, (targets[col] + r) % self._capacity))
            # bound host-side staging memory (and batch the upload) during long
            # collection-only phases such as the learning_starts prefill
            if len(self._staged) >= 8 * self.FLUSH_BUCKET:
                self._flush()

    def force_done_last(self, env: int) -> None:
        """Fault-tolerance patch (reference dreamer_v3.py:642-650): mark the
        most recent stored step of ``env`` as terminal on both copies."""
        with self._write_lock or nullcontext():
            sub = self._rb.buffer[env]
            last_idx = (sub._pos - 1) % sub.buffer_size
            sub["dones"][last_idx] = np.ones_like(sub["dones"][last_idx])
            if "is_first" in sub:
                # DV1-family buffers store no is_first column; keep behavior
                # identical to the host-path patch (staging.py)
                sub["is_first"][last_idx] = np.zeros_like(sub["is_first"][last_idx])
            self._staged.append((env, int(last_idx)))

    # -- device plumbing ---------------------------------------------------

    def _allocate(self, example_row: Dict[str, np.ndarray]) -> None:
        import jax
        import jax.numpy as jnp

        # every shard is (capacity + overlap) x group_envs of EVERY key in HBM
        rows = self._capacity + self._overlap
        max_group = max(len(g) for g in self._groups)
        bytes_per_row = sum(
            int(np.prod(np.asarray(v).shape)) * np.asarray(v).dtype.itemsize * max_group
            for v in example_row.values()
        )
        _check_hbm_budget(
            self._homes[0],
            rows,
            bytes_per_row,
            "DeviceRingReplay",
            lambda limit: int(0.5 * limit / max(bytes_per_row, 1)) - self._overlap,
            self._n_envs,
        )
        self._shards = []
        for g, envs in enumerate(self._groups):
            with jax.default_device(self._homes[g]):
                self._shards.append(
                    {
                        k: jnp.zeros(
                            (rows, len(envs)) + np.asarray(v).shape, np.asarray(v).dtype
                        )
                        for k, v in example_row.items()
                    }
                )

    def _scatter_fn(self, n_rows: int):
        import jax

        fn = self._scatter_fns.get(n_rows)
        if fn is None:
            def scatter(buf, t_idx, e_idx, rows):
                return {
                    k: v.at[t_idx, e_idx].set(rows[k], mode="drop")
                    for k, v in buf.items()
                }

            fn = jax.jit(scatter, donate_argnums=(0,))
            self._scatter_fns[n_rows] = fn
        return fn

    def _flush(self) -> None:
        import jax

        if not self._staged:
            return
        # dedupe (env, t) slots: XLA's scatter leaves the winner among
        # duplicate indices undefined, and duplicates are legal here
        # (force_done_last re-stages the slot its add() just wrote; a ring
        # can wrap within one staging window). Values are read from the host
        # buffer, which always holds the newest write for a slot.
        slots = list(dict.fromkeys(self._staged))
        sub0 = self._rb.buffer[slots[0][0]]
        if self._shards is None:
            self._allocate({k: _as_np(v)[0, 0] for k, v in sub0._buf.items()})
        # head rows are mirrored into the shadow region past the tail so
        # wrapped sequences stay physically contiguous (value read from the
        # same host slot)
        slots.extend([(env, t + self._capacity) for env, t in slots if t < self._overlap])
        slots_arr = np.asarray(slots, np.int64).reshape(len(slots), 2)
        envs, ts = slots_arr[:, 0], slots_arr[:, 1] % self._capacity
        oob = self._capacity + self._overlap
        for g in range(len(self._groups)):
            sel = np.nonzero(self._env_group[envs] == g)[0]
            if sel.size == 0:
                continue
            n = int(sel.size)
            padded = _pad_rows(n)
            t_idx = np.full(padded, oob, np.int32)  # OOB → dropped
            e_idx = np.zeros(padded, np.int32)
            t_idx[:n] = slots_arr[sel, 1]
            e_idx[:n] = self._env_col[envs[sel]]
            # group slots by env and gather each env's rows with one
            # fancy-index read (a per-row Python loop was thousands of small
            # copies per flush on a 1-core host, inside the acting timer);
            # the (src rows, dst positions) maps depend only on the env split
            by_env = {}
            for env in np.unique(envs[sel]):
                pos = sel[np.nonzero(envs[sel] == env)[0]]
                by_env[int(env)] = (pos, np.searchsorted(sel, pos))
            rows: Dict[str, np.ndarray] = {}
            for k, v0 in sub0._buf.items():
                first = _as_np(v0)[0, 0]
                stack = np.zeros((padded,) + first.shape, first.dtype)
                for env, (pos, dst) in by_env.items():
                    stack[dst] = _as_np(self._rb.buffer[env]._buf[k])[ts[pos], 0]
                rows[k] = stack
            payload = staged_device_put((t_idx, e_idx, rows), self._homes[g])
            self._shards[g] = self._scatter_fn(padded)(self._shards[g], *payload)
        self._staged.clear()

    # -- sample path -------------------------------------------------------

    def _plan_group(
        self, envs: np.ndarray, batch: int, sequence_length: int, n_samples: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Host-side index plan for one group, reusing the host buffers' own
        sampling logic (``pick_envs`` restricted to the group's envs + per-env
        ``plan_starts``).

        Returns ``(starts [n_samples * batch], cols [n_samples * batch])``
        ordered sample-major with per-env column groups, matching the host
        ``EnvIndependentReplayBuffer.sample`` concat layout. Starts are
        physical ring rows; a sequence always occupies the ``L`` contiguous
        rows from its start thanks to the shadow region.
        """
        L = sequence_length
        try:
            with_data, counts = self._rb.pick_envs(batch, self._rng, envs=[int(e) for e in envs])
        except ValueError as exc:
            # Intended behavior, made diagnosable: each device can only gather
            # from its LOCAL ring shard, so an empty group cannot borrow
            # another group's envs (the host path would sample globally; here
            # that would require a cross-device read that defeats the ring's
            # no-collective design). Groups fill in lockstep during normal
            # collection — this only triggers when e.g. a checkpoint taken
            # before every env had collected is restored under sharding.
            raise ValueError(
                f"Device-ring group {sorted(int(e) for e in envs)} has no "
                "samples while sampling was requested. Sharded rings sample "
                "per-group by design (device-local gathers); collect at least "
                "one sequence on every env group before sampling, or restore "
                "a checkpoint whose buffer covers all env groups."
            ) from exc
        starts_by_env: List[np.ndarray] = []
        envs_order: List[int] = []
        for j, env in enumerate(with_data):
            c = int(counts[j])
            if c == 0:
                continue
            starts = self._rb.buffer[env].plan_starts(c * n_samples, L, rng=self._rng)
            starts_by_env.append(np.asarray(starts).reshape(n_samples, c))
            envs_order.append(env)
        all_starts = np.concatenate(starts_by_env, axis=1)  # [n_samples, B]
        all_cols = np.concatenate(
            [
                np.full((n_samples, s.shape[1]), self._env_col[e], np.int32)
                for s, e in zip(starts_by_env, envs_order)
            ],
            axis=1,
        )
        return all_starts.reshape(-1).astype(np.int32), all_cols.reshape(-1).astype(np.int32)

    def _gather_fn(self, n_rows: int, L: int, n_samples: int):
        import jax

        key = (n_rows, L, n_samples)
        fn = self._gather_fns.get(key)
        if fn is None:
            def gather(buf, starts, e_idx):
                # contiguous-block reads (thanks to the shadow region): a
                # vmapped dynamic_slice lowers to a gather of [L, ...] BLOCKS,
                # not L scattered rows — the difference between ~ms and
                # ~hundreds of ms per sample on a GB-scale TPU ring
                def one(s, e):
                    return {
                        k: jax.lax.dynamic_slice(
                            v, (s, e) + (0,) * (v.ndim - 2), (L, 1) + v.shape[2:]
                        )[:, 0]
                        for k, v in buf.items()
                    }

                sel = jax.vmap(one)(starts, e_idx)  # {k: [total, L, ...]}
                out = {}
                for k, v in sel.items():
                    v = v.reshape((n_samples, n_rows // n_samples, L) + v.shape[2:])
                    out[k] = v.swapaxes(1, 2)  # [n_samples, L, B, ...]
                return out

            fn = jax.jit(gather)
            self._gather_fns[key] = fn
        return fn

    def sample_device(
        self, batch_size: int, sequence_length: int = 1, n_samples: int = 1
    ) -> Dict[str, Any]:
        """Gather ``[n_samples, sequence_length, batch, ...]`` batches on
        device. The only host→device traffic is the int32 index plan. With a
        ``batch_sharding`` the result is a global sharded Array whose batch
        slice *g* was gathered (and stays) on the device that consumes it."""
        import jax

        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        if sequence_length <= 0:
            raise ValueError(f"'sequence_length' ({sequence_length}) must be greater than 0")
        if sequence_length > max(self._overlap, 1) and any(
            b.full for b in self._rb.buffer
        ):
            raise ValueError(
                f"sequence_length {sequence_length} exceeds the ring's "
                f"sequence_overlap {self._overlap}; construct DeviceRingReplay "
                "with sequence_overlap >= the training sequence length"
            )
        n_groups = len(self._groups)
        if batch_size % n_groups != 0:
            raise ValueError(
                f"batch_size {batch_size} must divide evenly over the "
                f"{n_groups} batch shards"
            )
        with self._write_lock or nullcontext():
            self._flush()
            if self._shards is None:
                raise ValueError("No sample has been added to the buffer")
            b_local = batch_size // n_groups
            parts: List[Dict[str, Any]] = []
            for g, envs in enumerate(self._groups):
                starts, cols = self._plan_group(envs, b_local, sequence_length, n_samples)
                fn = self._gather_fn(starts.shape[0], sequence_length, n_samples)
                # the index plan is the ONLY host→device traffic of a ring
                # sample; counting it keeps the telemetry's bytes_staged_h2d an
                # honest total (and shows how little the ring ships vs host
                # staging)
                starts, cols = staged_device_put((starts, cols), self._homes[g])
                parts.append(fn(self._shards[g], starts, cols))
        if self._sharding is None:
            return parts[0]
        return _assemble_global(parts, self._sharding, self._replicas, 2, batch_size)


class DeviceRingTransitions:
    """Flat-transition device ring: wrap a :class:`ReplayBuffer` with a
    device-side mirror for SAC-style ``[n_samples, batch, ...]`` bursts.

    The sequence ring above serves the Dreamer family's
    ``EnvIndependentReplayBuffer``; this class serves the flat uniform-replay
    algos (SAC, SAC-AE, DroQ): ``add`` forwards to the host buffer and stages
    the written time rows for a lazy scatter; ``sample_device`` plans
    ``(t, env)`` pairs **with the host buffer's own**
    :meth:`ReplayBuffer.plan_transitions` (so the valid-window and
    ``sample_next_obs`` semantics cannot diverge from the host path) and
    gathers the batch on device — including the derived ``next_<obs_key>``
    rows at ``(t + 1) % capacity``, which never cross the host link at all.

    With ``batch_sharding`` (a ``[n_samples, batch, ...]`` sharding with only
    dim 1 sharded, e.g. ``P(None, 'data')``) the ring shards env-wise over the
    mesh exactly like the sequence ring: each device stores the columns of the
    envs homed on it and gathers the batch slice it consumes, assembled with
    ``make_array_from_single_device_arrays`` — no resharding collective.
    """

    #: host-side staging threshold: a flush is forced once 8x this many
    #: time rows are staged (bounds staging memory during collection-only
    #: phases); the scatter itself pads to power-of-two buckets (_pad_rows)
    FLUSH_BUCKET = 32

    def __init__(
        self,
        host_rb: ReplayBuffer,
        device: Optional[Any] = None,
        seed: Optional[int] = None,
        batch_sharding: Optional[Any] = None,
    ):
        import jax

        if isinstance(host_rb, EnvIndependentReplayBuffer):
            raise TypeError(
                "DeviceRingTransitions wraps a flat ReplayBuffer; use "
                "DeviceRingReplay for EnvIndependentReplayBuffer sequence rings"
            )
        self._rb = host_rb
        self._capacity = int(host_rb.buffer_size)
        self._n_envs = int(host_rb.n_envs)
        self._rng = np.random.default_rng(seed)
        self._sharding = batch_sharding

        if batch_sharding is not None:
            n_groups = _batch_shard_count(batch_sharding, 1, "[n_samples, batch, ...]")
            if self._n_envs < n_groups or self._n_envs % n_groups != 0:
                raise ValueError(
                    f"DeviceRingTransitions needs the same number of envs on "
                    f"every batch shard: n_envs={self._n_envs} does not divide "
                    f"over {n_groups} data-axis shards"
                )
            self._homes, self._replicas = _homes_for_sharding(batch_sharding, 1, n_groups)
        else:
            self._homes = [device if device is not None else jax.devices()[0]]
            self._replicas = [[]]

        n_groups = len(self._homes)
        self._groups: List[np.ndarray] = [
            np.asarray(g, np.int64) for g in np.array_split(np.arange(self._n_envs), n_groups)
        ]
        self._env_col = np.empty(self._n_envs, np.int64)
        for envs in self._groups:
            self._env_col[envs] = np.arange(len(envs))
        # index plans ship as ONE packed int32 per transition (t * width + col,
        # decoded on device): the plan is the only recurring host→device
        # upload of a ring sample, so halving it doubles the staging win
        self._group_width = len(self._groups[0])
        if self._capacity * self._group_width >= 2**31:
            raise ValueError(
                f"DeviceRingTransitions index plan would overflow int32: "
                f"{self._capacity} rows x {self._group_width} envs per shard "
                "(such a ring cannot fit in HBM anyway; lower buffer.size)"
            )

        # per-group device storage, allocated lazily on the first flush
        self._shards: Optional[List[Dict[str, Any]]] = None
        # staged time rows; values are read back from the host buffer at
        # flush time (it owns the newest copy of every slot)
        self._staged: List[int] = []
        self._scatter_fns: Dict[int, Any] = {}
        self._gather_fns: Dict[Tuple[int, int, bool], Any] = {}
        self._write_lock: Optional[Any] = None
        # True while the DEVICE shard holds rows the host buffer never saw
        # (jitted-scan collection writes via scatter_append/adopt_jit_state);
        # host reads (checkpoint state_dict) sync first
        self._host_stale = False
        # wrapping a buffer that already holds data (e.g. restored from a
        # checkpoint before the ring was constructed): mirror it now instead
        # of depending on wrap-then-load call order
        if not host_rb.empty:
            self._remirror_from_host()

    # -- proxied host surface ---------------------------------------------

    @property
    def host(self) -> ReplayBuffer:
        return self._rb

    @property
    def buffer(self):
        return self._rb.buffer

    @property
    def buffer_size(self) -> int:
        return self._rb.buffer_size

    @property
    def n_envs(self) -> int:
        return self._rb.n_envs

    @property
    def full(self) -> bool:
        return self._rb.full

    @property
    def empty(self) -> bool:
        return self._rb.empty

    @property
    def is_memmap(self) -> bool:
        return self._rb.is_memmap

    @property
    def n_groups(self) -> int:
        """Mesh batch shards this ring is split over (1 = single device)."""
        return len(self._groups)

    @property
    def _device(self):
        return self._homes[0]

    @property
    def _buf(self) -> Optional[Dict[str, Any]]:
        """Single-shard view (tests / single-device introspection)."""
        if self._shards is None:
            return None
        if len(self._shards) != 1:
            raise AttributeError("_buf is only defined for single-shard rings")
        return self._shards[0]

    def seed(self, seed: Optional[int] = None) -> None:
        self._rb.seed(seed)
        self._rng = np.random.default_rng(seed)

    def bind_write_lock(self, lock: Any) -> None:
        """Serialize ``add`` against a concurrent ``sample_device``."""
        self._write_lock = lock

    def state_dict(self) -> Dict[str, Any]:
        self.sync_host()
        return self._rb.state_dict()

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        """Restore the host buffer, then re-mirror its filled region to the
        device shards as one contiguous block upload per key per shard."""
        self._rb.load_state_dict(state)
        self._remirror_from_host()

    # -- in-jit write path (jitted-scan collection, envs/rollout) -----------

    #: the in-jit append the rollout engine composes into its lax.scan
    scatter_append = staticmethod(scatter_append)

    def jit_state(self, example_rows: Optional[Dict[str, np.ndarray]] = None) -> Tuple[Dict[str, Any], Any]:
        """Hand the ring's device storage to an in-jit writer.

        Returns ``(bufs, pos)``: the device arrays (leaves ``[capacity,
        n_envs, ...]``) and the int32 write head. The writer appends with
        :func:`scatter_append` (typically inside a ``lax.scan``, donating
        ``bufs``) and gives the result back via :meth:`adopt_jit_state`.
        ``example_rows`` (leaves ``[n_envs, ...]``) allocates storage on the
        first call of an empty ring. Single-shard rings only: an in-jit
        writer owns exactly one device's storage.
        """
        if len(self._groups) != 1:
            raise ValueError(
                "jit_state requires a single-shard ring: the jitted-scan "
                "collection path owns one device's storage (env-sharded "
                "multi-device collection is not supported yet)"
            )
        with self._write_lock or nullcontext():
            self._flush()
            if self._shards is None:
                if example_rows is None:
                    raise ValueError(
                        "jit_state on an empty ring needs example_rows to "
                        "allocate storage"
                    )
                self._allocate({k: np.asarray(v) for k, v in example_rows.items()})
        import jax.numpy as jnp

        return self._shards[0], jnp.int32(self._rb._pos)

    def adopt_jit_state(self, bufs: Dict[str, Any], steps: int, example_rows: Dict[str, np.ndarray]) -> None:
        """Take back ring storage an in-jit writer advanced by ``steps`` time
        rows: the device arrays become the ring's storage and the host
        buffer's ring counters advance (``ReplayBuffer.advance_external``)
        so index planning stays correct — the rows themselves stay on
        device until a host read forces :meth:`sync_host`."""
        if len(self._groups) != 1:
            raise ValueError("adopt_jit_state requires a single-shard ring")
        with self._write_lock or nullcontext():
            self._shards = [bufs]
            self._rb.advance_external(example_rows, int(steps))
            self._host_stale = True

    def adopt_slab(self, rows: Dict[str, np.ndarray], n_valid: Optional[int] = None) -> int:
        """Zero-dispatch slab adoption: land a trajectory slab's valid rows
        in HBM directly — the plane's shared-memory slab views are the
        *source* of one ``device_put`` at their exact size, scattered into
        the ring at the positions a host ``add`` would have written.

        This removes both costs of the historical slab → host rb → ring
        path: the host-buffer row copy, and the flush's power-of-two row
        padding (``_pad_rows``) on the host→HBM upload — ``bytes_staged_h2d``
        for an adopted burst is the payload size, not up to 2×. The host
        ring counters advance via ``advance_external`` (planning and the
        staleness stamp stay correct); the host *data* goes stale until
        :meth:`sync_host`, exactly like the jitted-scan adoption path.

        ``rows`` leaves are ``[T, n_envs, ...]``; ``n_valid`` adopts only the
        first ``n_valid`` rows (a partial slab). Single-shard rings only.
        Returns the bytes staged over the host→HBM link.
        """
        if len(self._groups) != 1:
            raise ValueError(
                "adopt_slab requires a single-shard ring: a slab lands on "
                "one device's storage (env-sharded adoption is not "
                "supported yet)"
            )
        rows = {k: np.asarray(v) for k, v in rows.items()}
        first = next(iter(rows.values()))
        steps = int(first.shape[0] if n_valid is None else n_valid)
        if steps <= 0:
            return 0
        with self._write_lock or nullcontext():
            self._flush()  # earlier host-buffered adds must land first
            if self._shards is None:
                self._allocate({k: v[0] for k, v in rows.items()})
            # same trailing-window rule as ReplayBuffer.add for oversize data
            write_len = min(steps, self._capacity)
            start = int(self._rb._pos) + steps - write_len
            t_idx = (np.arange(start, start + write_len) % self._capacity).astype(np.int32)
            payload = {
                k: np.ascontiguousarray(v[steps - write_len : steps]) for k, v in rows.items()
            }
            dev = staged_device_put((t_idx, payload), self._homes[0])
            self._shards[0] = self._scatter_fn(write_len)(self._shards[0], *dev)
            self._rb.advance_external({k: v[0] for k, v in rows.items()}, steps)
            self._host_stale = True
        add_replay_adoption()
        return int(sum(v.nbytes for v in payload.values()) + t_idx.nbytes)

    def sync_host(self) -> None:
        """Download the device ring into the host buffer (one device_get per
        key) if in-jit writes left it stale. Called before any host read of
        the buffer data — checkpoint ``state_dict`` does it automatically.
        Only the valid window (``capacity`` if full, else ``_pos`` rows,
        padded to a power of two to bound slice-program compiles like
        ``_pad_rows``) crosses the link — an early checkpoint of a large
        HBM ring must not download gigabytes of unwritten zeros."""
        if not self._host_stale:
            return
        import jax

        with self._write_lock or nullcontext():
            if self._shards is not None and self._rb.buffer is not None:
                n_rows = self._capacity if self._rb.full else int(self._rb._pos)
                n_get = min(self._capacity, _pad_rows(n_rows)) if n_rows else 0
                if n_get:
                    rows = jax.device_get(
                        {k: v[:n_get] for k, v in self._shards[0].items()}
                    )
                    for k, v in rows.items():
                        self._rb.buffer[k][:n_get] = v
            self._host_stale = False

    def _remirror_from_host(self) -> None:
        """Rebuild the device shards from whatever the host buffer holds —
        after a checkpoint restore, or at construction when wrapping a buffer
        that was filled/restored before the ring existed."""
        import jax

        self._shards = None
        self._staged.clear()
        self._host_stale = False
        if self._rb.buffer is None:
            return
        n_rows = self._capacity if self._rb.full else int(self._rb._pos)
        if n_rows == 0:
            return
        example = {k: _as_np(v)[0] for k, v in self._rb.buffer.items()}
        self._allocate(example)
        set_block = jax.jit(
            lambda buf, blk: {k: v.at[: next(iter(blk.values())).shape[0]].set(blk[k]) for k, v in buf.items()},
            donate_argnums=(0,),
        )
        for g, envs in enumerate(self._groups):
            blocks = {
                k: np.ascontiguousarray(_as_np(v)[:n_rows][:, envs])
                for k, v in self._rb.buffer.items()
            }
            blocks = staged_device_put(blocks, self._homes[g])
            self._shards[g] = set_block(self._shards[g], blocks)

    # -- write path --------------------------------------------------------

    def add(self, data: Dict[str, np.ndarray], validate_args: bool = False) -> None:
        with self._write_lock or nullcontext():
            pos_before = int(self._rb._pos)
            self._rb.add(data, validate_args=validate_args)
            data_len = next(iter(data.values())).shape[0]
            # the host keeps only the trailing window of an oversized insert;
            # mirror exactly the rows it wrote
            write_len = min(data_len, self._capacity)
            start = pos_before + data_len - write_len
            self._staged.extend((start + r) % self._capacity for r in range(write_len))
            # bound host-side staging memory (and batch the upload) during
            # long collection-only phases such as the learning_starts prefill
            if len(self._staged) >= 8 * self.FLUSH_BUCKET:
                self._flush()

    # -- device plumbing ---------------------------------------------------

    def _allocate(self, example_row: Dict[str, np.ndarray]) -> None:
        """``example_row`` leaves are per-env rows ``[n_envs, ...]``."""
        import jax
        import jax.numpy as jnp

        max_group = max(len(g) for g in self._groups)
        bytes_per_row = sum(
            int(np.prod(np.asarray(v).shape[1:], dtype=np.int64))
            * np.asarray(v).dtype.itemsize
            * max_group
            for v in example_row.values()
        )
        _check_hbm_budget(
            self._homes[0],
            self._capacity,
            bytes_per_row,
            "DeviceRingTransitions",
            lambda limit: int(0.5 * limit / max(bytes_per_row, 1)),
            self._n_envs,
        )
        self._shards = []
        for g, envs in enumerate(self._groups):
            with jax.default_device(self._homes[g]):
                self._shards.append(
                    {
                        k: jnp.zeros(
                            (self._capacity, len(envs)) + np.asarray(v).shape[1:],
                            np.asarray(v).dtype,
                        )
                        for k, v in example_row.items()
                    }
                )

    def _scatter_fn(self, n_rows: int):
        import jax

        fn = self._scatter_fns.get(n_rows)
        if fn is None:
            def scatter(buf, t_idx, rows):
                return {
                    k: v.at[t_idx].set(rows[k], mode="drop") for k, v in buf.items()
                }

            fn = jax.jit(scatter, donate_argnums=(0,))
            self._scatter_fns[n_rows] = fn
        return fn

    def _flush(self) -> None:
        if not self._staged:
            return
        # dedupe staged rows: a ring can wrap within one staging window, and
        # XLA's scatter leaves the winner among duplicate indices undefined;
        # values are read from the host buffer, which holds the newest write
        rows_t = np.asarray(list(dict.fromkeys(self._staged)), np.int64)
        host = self._rb.buffer
        if self._shards is None:
            self._allocate({k: _as_np(v)[0] for k, v in host.items()})
        n = int(rows_t.size)
        padded = _pad_rows(n)
        t_idx = np.full(padded, self._capacity, np.int32)  # OOB → dropped
        t_idx[:n] = rows_t
        for g, envs in enumerate(self._groups):
            rows: Dict[str, np.ndarray] = {}
            for k, v in host.items():
                arr = _as_np(v)
                stack = np.zeros((padded, len(envs)) + arr.shape[2:], arr.dtype)
                # fused row+column gather: copies only this group's columns
                # (arr[rows_t][:, envs] would materialize the full width
                # n_groups times per flush)
                stack[:n] = arr[np.ix_(rows_t, envs)]
                rows[k] = stack
            payload = staged_device_put((t_idx, rows), self._homes[g])
            self._shards[g] = self._scatter_fn(padded)(self._shards[g], *payload)
        self._staged.clear()

    # -- sample path -------------------------------------------------------

    def _gather_fn(self, total: int, n_samples: int, sample_next_obs: bool):
        import jax
        import jax.numpy as jnp

        key = (total, n_samples, sample_next_obs)
        fn = self._gather_fns.get(key)
        if fn is None:
            capacity = self._capacity
            width = self._group_width
            obs_keys = tuple(self._rb._obs_keys)

            def gather(buf, plan):
                # plan rows are packed t * width + col (int32): one upload
                # word per transition instead of two
                t_idx = plan // width
                c_idx = plan % width
                out = {}
                for k, v in buf.items():
                    sel = v[t_idx, c_idx]
                    out[k] = sel.reshape((n_samples, total // n_samples) + sel.shape[1:])
                    if sample_next_obs and k in obs_keys:
                        nxt = v[jnp.mod(t_idx + 1, capacity), c_idx]
                        out[f"next_{k}"] = nxt.reshape(
                            (n_samples, total // n_samples) + nxt.shape[1:]
                        )
                return out

            fn = jax.jit(gather)
            self._gather_fns[key] = fn
        return fn

    def sample_device(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
    ) -> Dict[str, Any]:
        """Gather ``[n_samples, batch, ...]`` transition batches on device.

        The only host→device traffic is the int32 index plan. With a
        ``batch_sharding`` the result is a global sharded Array whose batch
        slice *g* was gathered (and stays) on the device that consumes it."""
        import jax

        n_groups = len(self._groups)
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        if batch_size % n_groups != 0:
            raise ValueError(
                f"batch_size {batch_size} must divide evenly over the "
                f"{n_groups} batch shards"
            )
        with self._write_lock or nullcontext():
            self._flush()
            if self._shards is None:
                raise ValueError("No sample has been added to the buffer")
            b_local = batch_size // n_groups
            parts: List[Dict[str, Any]] = []
            for g, envs in enumerate(self._groups):
                # the host buffer's own planner: valid-window semantics live in
                # exactly one place; per-group runs restrict the env draw to
                # the group's columns (uniform within the group, like the
                # sequence ring's per-group pick_envs)
                t_idx, e_idx = self._rb.plan_transitions(
                    b_local,
                    sample_next_obs=sample_next_obs,
                    n_samples=n_samples,
                    rng=self._rng,
                    envs=None if n_groups == 1 else envs,
                )
                packed = (
                    t_idx.astype(np.int64) * self._group_width + self._env_col[e_idx]
                ).astype(np.int32)
                fn = self._gather_fn(packed.shape[0], n_samples, sample_next_obs)
                plan = staged_device_put(packed, self._homes[g])
                parts.append(fn(self._shards[g], plan))
        if self._sharding is None:
            return parts[0]
        return _assemble_global(parts, self._sharding, self._replicas, 1, batch_size)
