"""Device-resident replay ring for sequence training.

TPU-native replacement for the reference's host-only replay staging
(``sheeprl/data/buffers.py:528-690`` + per-gradient-step host→device batch
copies): every transition crosses the host→HBM link **once**, when it is
collected, and gradient-step batches are *gathered on device* from a
resident uint8 ring. On a remote-attached chip (or any bandwidth-limited
host link) this turns the train round from transfer-bound into
compute-bound — a [64, 16] pixel batch that costs a 12.6 MB upload per
gradient step becomes an 8 KB index upload.

Design:

- The **host** :class:`~sheeprl_tpu.data.buffers.EnvIndependentReplayBuffer`
  stays the source of truth (checkpointing, fault-tolerance patches); this
  class wraps it and mirrors every ``add`` into a device ring of the same
  per-env geometry.
- **Index planning stays on the host and reuses the host buffers' own
  logic** (:meth:`SequentialReplayBuffer.plan_starts`,
  :meth:`EnvIndependentReplayBuffer.pick_envs`), so sampling semantics can
  never diverge between the two paths; only the final *gather* runs on
  device.
- Writes are **staged and flushed lazily** (one scatter per training burst,
  padded to shape buckets so XLA compiles a handful of programs); padding
  rows carry out-of-bounds targets and are dropped by the scatter
  (``mode="drop"``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, _as_np

__all__ = ["DeviceRingReplay"]


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


class DeviceRingReplay:
    """Wrap an :class:`EnvIndependentReplayBuffer` with a device-side mirror.

    ``add`` forwards to the host buffer and stages the same rows for the
    device ring; ``sample_device`` returns a dict of **device** arrays shaped
    ``[n_samples, sequence_length, batch, ...]`` (the same layout as the host
    ``sample``), produced by an on-device gather.
    """

    #: flush scatters are padded to multiples of this many rows so repeated
    #: bursts reuse a few compiled programs instead of one per row count
    FLUSH_BUCKET = 32

    def __init__(
        self,
        host_rb: EnvIndependentReplayBuffer,
        device: Optional[Any] = None,
        seed: Optional[int] = None,
        sequence_overlap: int = 64,
    ):
        import jax

        self._rb = host_rb
        self._capacity = int(host_rb.buffer_size)
        self._n_envs = int(host_rb.n_envs)
        # Shadow region: the first `overlap` rows are mirrored past the tail
        # so every sequence of length ≤ overlap is PHYSICALLY contiguous even
        # when it wraps, and sampling can read contiguous blocks (vmapped
        # dynamic_slice) instead of row-scattered gathers — on TPU a gather
        # of thousands of random 12 KB rows from a GB-scale ring is ~100x
        # slower than the same bytes as contiguous block DMA (measured:
        # ~0.5 s/sample at 100k rows vs ~ms for blocks).
        self._overlap = max(0, min(int(sequence_overlap), self._capacity))
        self._device = device if device is not None else jax.devices()[0]
        self._rng = np.random.default_rng(seed)
        # device storage, allocated lazily on the first add (dtypes/shapes
        # are discovered from the data, like the host buffer does)
        self._buf: Optional[Dict[str, Any]] = None
        # staged (env, target_index) slots; row *values* are read back from
        # the host buffer at flush time (it owns the newest copy of every
        # slot, so no per-step duplicate row copies are held here)
        self._staged: List[Tuple[int, int]] = []
        self._scatter_fns: Dict[int, Any] = {}
        self._gather_fns: Dict[Tuple[int, int, int], Any] = {}

    # -- proxied host surface ---------------------------------------------

    @property
    def host(self) -> EnvIndependentReplayBuffer:
        return self._rb

    @property
    def buffer(self):
        return self._rb.buffer

    @property
    def buffer_size(self) -> int:
        return self._rb.buffer_size

    @property
    def n_envs(self) -> int:
        return self._rb.n_envs

    def seed(self, seed: Optional[int] = None) -> None:
        self._rb.seed(seed)
        self._rng = np.random.default_rng(seed)

    def state_dict(self) -> Dict[str, Any]:
        return self._rb.state_dict()

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        """Restore the host buffer, then re-mirror its filled region to the
        device as one contiguous block upload per key."""
        import jax

        self._rb.load_state_dict(state)
        self._buf = None
        self._staged.clear()
        n_rows = np.zeros(self._n_envs, np.int64)
        example: Optional[Dict[str, np.ndarray]] = None
        for env, sub in enumerate(self._rb.buffer):
            if sub._buf is None:
                continue
            n_rows[env] = sub.buffer_size if sub.full else sub._pos
            if example is None:
                example = {k: _as_np(v)[0, 0] for k, v in sub._buf.items()}
        max_rows = int(n_rows.max()) if example is not None else 0
        if max_rows == 0:
            return
        self._allocate(example)
        blocks: Dict[str, np.ndarray] = {}
        for k, v0 in example.items():
            block = np.zeros((max_rows, self._n_envs) + np.asarray(v0).shape, np.asarray(v0).dtype)
            for env, sub in enumerate(self._rb.buffer):
                if sub._buf is not None and n_rows[env] > 0:
                    block[: n_rows[env], env] = _as_np(sub._buf[k])[: n_rows[env], 0]
            blocks[k] = block
        cap, ov = self._capacity, self._overlap

        def _set(v, b):
            v = v.at[: b.shape[0]].set(b)
            if ov:
                # mirror the head into the shadow region
                v = v.at[cap:].set(v[:ov])
            return v

        set_block = jax.jit(
            lambda buf, blk: {k: _set(v, blk[k]) for k, v in buf.items()},
            donate_argnums=(0,),
        )
        self._buf = set_block(self._buf, blocks)

    # -- write path --------------------------------------------------------

    def add(
        self,
        data: Dict[str, np.ndarray],
        env_idxes: Optional[Sequence[int]] = None,
        validate_args: bool = False,
    ) -> None:
        if env_idxes is None:
            env_idxes = list(range(self._n_envs))
        # capture write targets before the host add advances them (and let a
        # failing host add leave the mirror untouched)
        targets = [int(self._rb.buffer[env]._pos) for env in env_idxes]
        self._rb.add(data, env_idxes, validate_args=validate_args)
        rows = next(iter(data.values())).shape[0]
        for col, env in enumerate(env_idxes):
            for r in range(rows):
                self._staged.append((env, (targets[col] + r) % self._capacity))
        # bound host-side staging memory (and batch the upload) during long
        # collection-only phases such as the learning_starts prefill
        if len(self._staged) >= 8 * self.FLUSH_BUCKET:
            self._flush()

    def force_done_last(self, env: int) -> None:
        """Fault-tolerance patch (reference dreamer_v3.py:642-650): mark the
        most recent stored step of ``env`` as terminal on both copies."""
        sub = self._rb.buffer[env]
        last_idx = (sub._pos - 1) % sub.buffer_size
        sub["dones"][last_idx] = np.ones_like(sub["dones"][last_idx])
        sub["is_first"][last_idx] = np.zeros_like(sub["is_first"][last_idx])
        self._staged.append((env, int(last_idx)))

    # -- device plumbing ---------------------------------------------------

    def _allocate(self, example_row: Dict[str, np.ndarray]) -> None:
        import warnings

        import jax
        import jax.numpy as jnp

        # the ring is (capacity + overlap) x n_envs of EVERY key in HBM; with
        # DV3's default buffer.size=1e6 of 64x64x3 uint8 pixels that is ~12 GB
        # before model/optimizer state. Fail with the computed size (and the
        # size that fits) instead of an opaque XLA allocation error later.
        rows = self._capacity + self._overlap
        bytes_per_row = sum(
            int(np.prod(np.asarray(v).shape)) * np.asarray(v).dtype.itemsize * self._n_envs
            for v in example_row.values()
        )
        total = rows * bytes_per_row
        limit = None
        try:
            stats = self._device.memory_stats()
            limit = stats.get("bytes_limit") if stats else None
        except Exception:
            pass
        if limit and total > 0.95 * limit:
            # certain OOM: the ring alone leaves no room for params/optimizer
            fit_rows = max(int(0.5 * limit / max(bytes_per_row, 1)) - self._overlap, 0)
            raise ValueError(
                f"DeviceRingReplay would allocate {total / 2**30:.2f} GiB "
                f"({rows} rows x {bytes_per_row} B) on a device with a "
                f"{limit / 2**30:.2f} GiB limit; a ring of <= {fit_rows} per-env "
                f"rows fits in half the device (buffer.size <= "
                f"{fit_rows * self._n_envs} under the buffer.size//n_envs "
                "convention), or disable buffer.device_ring"
            )
        if (limit and total > 0.6 * limit) or total > 4 * 2**30:
            warnings.warn(
                f"DeviceRingReplay allocating {total / 2**30:.2f} GiB of HBM "
                f"({rows} per-env rows x {bytes_per_row} B"
                + (f", device limit {limit / 2**30:.2f} GiB" if limit else "")
                + "); lower buffer.size if the device OOMs",
                UserWarning,
            )
        with jax.default_device(self._device):
            self._buf = {
                k: jnp.zeros(
                    (self._capacity + self._overlap, self._n_envs) + np.asarray(v).shape,
                    np.asarray(v).dtype,
                )
                for k, v in example_row.items()
            }

    def _scatter_fn(self, n_rows: int):
        import jax

        fn = self._scatter_fns.get(n_rows)
        if fn is None:
            def scatter(buf, t_idx, e_idx, rows):
                return {
                    k: v.at[t_idx, e_idx].set(rows[k], mode="drop")
                    for k, v in buf.items()
                }

            fn = jax.jit(scatter, donate_argnums=(0,))
            self._scatter_fns[n_rows] = fn
        return fn

    def _flush(self) -> None:
        if not self._staged:
            return
        # dedupe (env, t) slots: XLA's scatter leaves the winner among
        # duplicate indices undefined, and duplicates are legal here
        # (force_done_last re-stages the slot its add() just wrote; a ring
        # can wrap within one staging window). Values are read from the host
        # buffer, which always holds the newest write for a slot.
        slots = list(dict.fromkeys(self._staged))
        sub0 = self._rb.buffer[slots[0][0]]
        if self._buf is None:
            self._allocate({k: _as_np(v)[0, 0] for k, v in sub0._buf.items()})
        # head rows are mirrored into the shadow region past the tail so
        # wrapped sequences stay physically contiguous (value read from the
        # same host slot)
        slots.extend([(env, t + self._capacity) for env, t in slots if t < self._overlap])
        n = len(slots)
        padded = _round_up(n, self.FLUSH_BUCKET)
        oob = self._capacity + self._overlap
        t_idx = np.full(padded, oob, np.int32)  # OOB → dropped
        e_idx = np.zeros(padded, np.int32)
        slots_arr = np.asarray(slots, np.int64).reshape(n, 2)
        envs, ts = slots_arr[:, 0], slots_arr[:, 1] % self._capacity
        # group slots by env and gather each env's rows with one fancy-index
        # read (the per-row Python loop was thousands of small copies per
        # flush on a 1-core host, inside the env-interaction timer)
        by_env = {int(env): np.nonzero(envs == env)[0] for env in np.unique(envs)}
        rows: Dict[str, np.ndarray] = {}
        for k, v0 in sub0._buf.items():
            first = _as_np(v0)[0, 0]
            stack = np.zeros((padded,) + first.shape, first.dtype)
            for env, pos in by_env.items():
                stack[pos] = _as_np(self._rb.buffer[env]._buf[k])[ts[pos], 0]
            rows[k] = stack
        t_idx[:n] = slots_arr[:, 1]
        e_idx[:n] = envs
        self._buf = self._scatter_fn(padded)(self._buf, t_idx, e_idx, rows)
        self._staged.clear()

    # -- sample path -------------------------------------------------------

    def _plan_indices(
        self, batch_size: int, sequence_length: int, n_samples: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Host-side index plan reusing the host buffers' own sampling logic
        (``pick_envs`` + per-env ``plan_starts``).

        Returns ``(starts [n_samples * batch], e_idx [n_samples * batch])``
        ordered sample-major with per-env column groups, matching the host
        ``EnvIndependentReplayBuffer.sample`` concat layout. Starts are
        physical ring rows; a sequence always occupies the ``L`` contiguous
        rows from its start thanks to the shadow region.
        """
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        if sequence_length <= 0:
            raise ValueError(f"'sequence_length' ({sequence_length}) must be greater than 0")
        L = sequence_length
        with_data, counts = self._rb.pick_envs(batch_size, self._rng)
        starts_by_env: List[np.ndarray] = []
        envs_order: List[int] = []
        for j, env in enumerate(with_data):
            c = int(counts[j])
            if c == 0:
                continue
            starts = self._rb.buffer[env].plan_starts(c * n_samples, L, rng=self._rng)
            starts_by_env.append(np.asarray(starts).reshape(n_samples, c))
            envs_order.append(env)
        # sample-major: [n_samples, B] starts / envs, flattened
        all_starts = np.concatenate(starts_by_env, axis=1)  # [n_samples, B]
        all_envs = np.concatenate(
            [np.full((n_samples, s.shape[1]), e, np.int32) for s, e in zip(starts_by_env, envs_order)],
            axis=1,
        )
        return all_starts.reshape(-1).astype(np.int32), all_envs.reshape(-1).astype(np.int32)

    def _gather_fn(self, n_rows: int, L: int, n_samples: int):
        import jax

        key = (n_rows, L, n_samples)
        fn = self._gather_fns.get(key)
        if fn is None:
            def gather(buf, starts, e_idx):
                # contiguous-block reads (thanks to the shadow region): a
                # vmapped dynamic_slice lowers to a gather of [L, ...] BLOCKS,
                # not L scattered rows — the difference between ~ms and
                # ~hundreds of ms per sample on a GB-scale TPU ring
                def one(s, e):
                    return {
                        k: jax.lax.dynamic_slice(
                            v, (s, e) + (0,) * (v.ndim - 2), (L, 1) + v.shape[2:]
                        )[:, 0]
                        for k, v in buf.items()
                    }

                sel = jax.vmap(one)(starts, e_idx)  # {k: [total, L, ...]}
                out = {}
                for k, v in sel.items():
                    v = v.reshape((n_samples, n_rows // n_samples, L) + v.shape[2:])
                    out[k] = v.swapaxes(1, 2)  # [n_samples, L, B, ...]
                return out

            fn = jax.jit(gather)
            self._gather_fns[key] = fn
        return fn

    def sample_device(
        self, batch_size: int, sequence_length: int = 1, n_samples: int = 1
    ) -> Dict[str, Any]:
        """Gather ``[n_samples, sequence_length, batch, ...]`` batches on
        device. The only host→device traffic is the int32 index plan."""
        if sequence_length > max(self._overlap, 1) and any(
            b.full for b in self._rb.buffer
        ):
            raise ValueError(
                f"sequence_length {sequence_length} exceeds the ring's "
                f"sequence_overlap {self._overlap}; construct DeviceRingReplay "
                "with sequence_overlap >= the training sequence length"
            )
        self._flush()
        if self._buf is None:
            raise ValueError("No sample has been added to the buffer")
        starts, e_idx = self._plan_indices(batch_size, sequence_length, n_samples)
        fn = self._gather_fn(starts.shape[0], sequence_length, n_samples)
        return fn(self._buf, starts, e_idx)
