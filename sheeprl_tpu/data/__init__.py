from sheeprl_tpu.data.buffers import (
    EnvIndependentReplayBuffer,
    EpisodeBuffer,
    ReplayBuffer,
    SequentialReplayBuffer,
)

__all__ = [
    "EnvIndependentReplayBuffer",
    "EpisodeBuffer",
    "ReplayBuffer",
    "SequentialReplayBuffer",
]
