from sheeprl_tpu.data.buffers import (
    EnvIndependentReplayBuffer,
    EpisodeBuffer,
    ReplayBuffer,
    SequentialReplayBuffer,
)
from sheeprl_tpu.data.device_ring import DeviceRingReplay, DeviceRingTransitions
from sheeprl_tpu.data.staging import (
    HostStaging,
    ReplayStaging,
    RingStaging,
    make_replay_staging,
)

__all__ = [
    "DeviceRingReplay",
    "DeviceRingTransitions",
    "EnvIndependentReplayBuffer",
    "EpisodeBuffer",
    "HostStaging",
    "ReplayBuffer",
    "ReplayStaging",
    "RingStaging",
    "SequentialReplayBuffer",
    "make_replay_staging",
]
