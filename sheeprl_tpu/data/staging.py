"""Shared replay-staging facade: one code path from host buffer to HBM.

Every off-policy train loop used to hand-roll the same block — ``rb.sample``
on the host, reshape, ``jax.device_put`` to the burst sharding — which is
exactly the synchronous host→HBM staging the paper's thesis says to avoid
(transitions should cross the link once, at collection time). This module is
the single chokepoint for that decision:

``make_replay_staging(cfg, fabric, rb, ...)`` returns a staging object whose
``sample_device(...)`` yields the train burst as **device** arrays:

- ``buffer.device_ring=True`` (single-process): the replay buffer is wrapped
  in a device-resident ring (:mod:`sheeprl_tpu.data.device_ring`) — sequence
  mode for the Dreamer family's ``EnvIndependentReplayBuffer``, flat
  transition mode for SAC-style ``ReplayBuffer`` — and bursts are *gathered
  on device*; the only per-burst upload is the int32 index plan.
- otherwise (ring off, multi-process, or an unsupported buffer type): a
  **double-buffered prefetch pipeline** — a worker thread plans indices,
  samples, and ``device_put``\\ s burst *k+1* while the train program runs
  burst *k* (the same overlap measured at 1.43–3.1× in BENCH_DECOUPLED.md),
  so even the host fallback hides sampling + H2D behind device compute.
  ``buffer.prefetch=False`` degrades to the plain synchronous path (useful
  when bitwise run-to-run determinism matters more than overlap: prefetching
  draws burst *k+1*'s indices before the env steps collected during burst
  *k+1* land, and the worker's rng interleaving is scheduling-dependent).

The ``n_samples`` axis this facade stages is the contract with the fused
train-burst engine (:mod:`sheeprl_tpu.train`, howto/train_burst.md): the
``[n_samples, ...]`` stack ``sample_device`` returns is consumed as ONE
scanned device program per gradient burst — staging produces the block,
the burst scans it, and neither side pays a per-gradient-step dispatch.

Telemetry: ring gathers bump ``ring_gathers``; pipeline bursts bump
``prefetch_hits``/``prefetch_misses`` and ``prefetch_wait_ms`` (the residue a
train step still blocked on a not-yet-ready prefetched batch) — all beside
``bytes_staged_h2d`` in telemetry.json, so "is the data path overlapped?" is
a number, not a guess. Enforced as the only staging path in ``algos/`` by
``tools/lint_staging.py``.
"""

from __future__ import annotations

import threading
import time
import warnings
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, Optional, Tuple

import numpy as np

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, EpisodeBuffer
from sheeprl_tpu.data.device_ring import DeviceRingReplay, DeviceRingTransitions
from sheeprl_tpu.obs.counters import add_prefetch, add_ring_gather, count_h2d
from sheeprl_tpu.obs.dist.staleness import note_queue_depth

__all__ = ["HostStaging", "ReplayStaging", "RingStaging", "make_replay_staging"]

# burst spec: (batch_size, sequence_length, n_samples, sample_next_obs)
_Spec = Tuple[int, int, int, bool]


class ReplayStaging:
    """Common surface of the two staging strategies.

    ``rb`` is the buffer the train loop should keep using for ``add`` /
    checkpointing — the ring wrapper when the ring is on (it mirrors every
    ``add`` to HBM and proxies ``state_dict``), the original host buffer
    otherwise.
    """

    is_ring = False

    def __init__(self, rb: Any):
        self._rb = rb

    @property
    def rb(self) -> Any:
        return self._rb

    @property
    def supports_adoption(self) -> bool:
        """True when :meth:`adopt_slab` can land a slab straight in HBM."""
        return False

    def adopt_slab(self, rows: Dict[str, np.ndarray], n_valid: Optional[int] = None) -> int:
        """Zero-dispatch slab adoption (device ring only) — see
        :meth:`~sheeprl_tpu.data.device_ring.DeviceRingTransitions.adopt_slab`."""
        raise NotImplementedError(
            "slab adoption needs the single-group device ring "
            "(buffer.device_ring=True on a 1-group mesh)"
        )

    def update_priorities(self, td_errors: np.ndarray) -> None:
        """TD-priority writeback for the last sampled burst (no-op unless the
        buffer is a prioritized ShardedReplay)."""
        if hasattr(self._rb, "update_priorities"):
            self._rb.update_priorities(td_errors)

    def last_weights(self) -> Optional[np.ndarray]:
        """Importance weights aligned with the last burst's flat row order
        (``None`` for unweighted sampling)."""
        if hasattr(self._rb, "last_weights"):
            return self._rb.last_weights()
        return None

    def sample_device(
        self,
        batch_size: int,
        *,
        sequence_length: Optional[int] = None,
        n_samples: int = 1,
        sample_next_obs: bool = False,
    ) -> Dict[str, Any]:
        raise NotImplementedError

    def force_done_last(self, env: int) -> None:
        """Mark env's most recent stored step terminal (restart-on-exception
        fault patch) on every copy of the data this staging keeps."""
        raise NotImplementedError

    def close(self) -> None:
        """Release background resources (prefetch worker). Idempotent."""


class RingStaging(ReplayStaging):
    """Device-ring staging: bursts are gathered from HBM-resident data."""

    is_ring = True

    def sample_device(
        self,
        batch_size: int,
        *,
        sequence_length: Optional[int] = None,
        n_samples: int = 1,
        sample_next_obs: bool = False,
    ) -> Dict[str, Any]:
        add_ring_gather()
        if isinstance(self._rb, DeviceRingReplay):
            return self._rb.sample_device(
                batch_size,
                sequence_length=int(sequence_length or 1),
                n_samples=n_samples,
            )
        return self._rb.sample_device(
            batch_size, sample_next_obs=sample_next_obs, n_samples=n_samples
        )

    @property
    def supports_adoption(self) -> bool:
        return isinstance(self._rb, DeviceRingTransitions) and self._rb.n_groups == 1

    def adopt_slab(self, rows: Dict[str, np.ndarray], n_valid: Optional[int] = None) -> int:
        return self._rb.adopt_slab(rows, n_valid)

    def force_done_last(self, env: int) -> None:
        self._rb.force_done_last(env)


class HostStaging(ReplayStaging):
    """Host-path staging: ``rb.sample`` → ``device_put``, double-buffered.

    With ``prefetch=True`` each ``sample_device`` call returns the burst the
    worker prepared during the previous train burst (when the burst spec
    repeats — the steady state) and immediately schedules the next one. The
    worker samples under a lock shared with the buffer's ``add`` (bound via
    ``bind_write_lock``), with ``clone=True`` so a later ring-wrap overwrite
    can never tear the staged rows; the ``device_put`` runs outside the lock.
    A spec is only prefetched once it has been requested twice, so one-off
    bursts (e.g. SAC's big learning-starts catch-up) don't leave a dead
    device-sized batch pinned in HBM.
    """

    #: bound on concurrently pending prefetched bursts (DroQ alternates two
    #: specs per update — critic and actor batches — so two slots are live)
    MAX_PENDING = 2

    def __init__(
        self,
        rb: Any,
        sharding: Any = None,
        *,
        sequence_mode: bool,
        prefetch: bool = True,
        lock: Optional[Any] = None,
    ):
        super().__init__(rb)
        self._sharding = sharding
        self._seq = bool(sequence_mode)
        self._lock = lock if lock is not None else threading.RLock()
        # another thread may mutate the buffer between a sample and its
        # device_put only when a worker or an external (decoupled) writer
        # exists; clone staged rows exactly then
        self._concurrent = bool(prefetch or lock is not None)
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pending: Dict[_Spec, Future] = {}
        self._spec_counts: Dict[_Spec, int] = {}
        if prefetch:
            if hasattr(rb, "bind_write_lock"):
                rb.bind_write_lock(self._lock)
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="replay-prefetch"
            )

    # -- produce one burst -------------------------------------------------

    def _target(self):
        if self._sharding is not None:
            return self._sharding
        import jax

        return jax.devices()[0]

    def _produce(self, spec: _Spec, clone: bool) -> Dict[str, Any]:
        import jax

        from sheeprl_tpu.obs.spans import span

        batch_size, seq_len, n_samples, sample_next_obs = spec
        with self._lock:
            if self._seq:
                np_batch = self._rb.sample(
                    batch_size,
                    n_samples=n_samples,
                    sequence_length=seq_len,
                    clone=clone,
                )
            else:
                # one plan of batch*n transitions, reshaped sample-major —
                # bitwise the layout the loops used to build by hand
                np_batch = self._rb.sample(
                    batch_size * n_samples,
                    sample_next_obs=sample_next_obs,
                    clone=clone,
                )
                np_batch = {
                    k: v.reshape((n_samples, batch_size) + v.shape[2:])
                    for k, v in np_batch.items()
                }
        # ship native dtypes (uint8 pixels = 4x less than f32 over the
        # host→HBM link) straight to the burst sharding; train steps
        # normalize on device
        with span("Time/stage_h2d_time", phase="stage_h2d"):
            out = jax.device_put(np_batch, self._target())
        count_h2d(np_batch)
        return out

    # -- public surface ----------------------------------------------------

    def sample_device(
        self,
        batch_size: int,
        *,
        sequence_length: Optional[int] = None,
        n_samples: int = 1,
        sample_next_obs: bool = False,
    ) -> Dict[str, Any]:
        spec: _Spec = (
            int(batch_size),
            int(sequence_length or 0),
            int(n_samples),
            bool(sample_next_obs),
        )
        if self._pool is None:
            return self._produce(spec, clone=self._concurrent)
        batch: Optional[Dict[str, Any]] = None
        fut = self._pending.pop(spec, None)
        if fut is not None:
            t0 = time.perf_counter()
            try:
                batch = fut.result()
            except Exception:
                # fall through to the sync produce: a genuine sampling error
                # re-raises there, on the caller thread with the caller's spec
                batch = None
            else:
                add_prefetch(hit=True, wait_ms=(time.perf_counter() - t0) * 1000.0)
        if batch is None:
            add_prefetch(hit=False)
            batch = self._produce(spec, clone=self._concurrent)
        count = self._spec_counts.get(spec, 0) + 1
        self._spec_counts[spec] = count
        if count >= 2 and spec not in self._pending:
            self._pending[spec] = self._pool.submit(self._produce, spec, True)
            while len(self._pending) > self.MAX_PENDING:
                # a stale pending burst pins device memory; drop oldest-first
                self._pending.pop(next(iter(self._pending))).cancel()
        # staleness gauge (obs/dist): in-flight prefetched bursts — 0 means
        # the pipeline is running dry, MAX_PENDING means it is saturated
        note_queue_depth("staging_prefetch", len(self._pending))
        return batch

    def update_priorities(self, td_errors: np.ndarray) -> None:
        # under the shared lock: the writeback touches the same per-shard
        # tables a concurrent planner reads
        if hasattr(self._rb, "update_priorities"):
            with self._lock:
                self._rb.update_priorities(td_errors)

    def force_done_last(self, env: int) -> None:
        if not isinstance(self._rb, EnvIndependentReplayBuffer):
            raise NotImplementedError(
                "force_done_last is only defined for per-env sequence buffers"
            )
        with self._lock:
            sub = self._rb.buffer[env]
            last_idx = (sub._pos - 1) % sub.buffer_size
            sub["dones"][last_idx] = np.ones_like(sub["dones"][last_idx])
            if "is_first" in sub:
                sub["is_first"][last_idx] = np.zeros_like(sub["is_first"][last_idx])

    def close(self) -> None:
        if self._pool is not None:
            for fut in self._pending.values():
                fut.cancel()
            self._pending.clear()
            self._pool.shutdown(wait=False)
            self._pool = None


def make_replay_staging(
    cfg: Any,
    fabric: Any,
    rb: Any,
    *,
    sequence_length: Optional[int] = None,
    batch_sharding: Any = None,
    seed: Optional[int] = None,
    lock: Optional[Any] = None,
) -> ReplayStaging:
    """Build the replay staging for one train loop.

    ``batch_sharding`` is the burst sharding the train step consumes —
    ``P(None, 'data')`` over ``[n_samples, batch, ...]`` for transition
    algos, ``P(None, None, 'data')`` over ``[n_samples, seq, batch, ...]``
    for sequence algos. ``lock`` lets decoupled loops share their
    player↔trainer buffer lock with the staging (pass an ``RLock``).
    """
    import jax

    sequence_mode = isinstance(rb, (EnvIndependentReplayBuffer, EpisodeBuffer))
    world_size = int(getattr(fabric, "world_size", 1) or 1) if fabric is not None else 1
    device = getattr(fabric, "device", None) if fabric is not None else None
    # sharded/prioritized replay (sheeprl_tpu/replay): the facade plans its
    # own cross-shard bursts on the host — duck-typed so data/ never imports
    # the replay package (replay imports data, not the reverse)
    is_sharded = hasattr(rb, "plan_burst")

    use_ring = bool(cfg.buffer.get("device_ring", False))
    if use_ring and is_sharded:
        warnings.warn(
            "buffer.device_ring=True is not supported with sharded or "
            "prioritized replay (replay.shards>1 or a non-uniform "
            "replay.strategy): the cross-shard planner samples on the host; "
            "falling back to the host prefetch pipeline."
        )
        use_ring = False
    if use_ring and jax.process_count() > 1:
        warnings.warn(
            "buffer.device_ring=True is not supported on multi-process "
            f"(multi-host) runs yet ({jax.process_count()} processes); "
            "falling back to the host prefetch pipeline."
        )
        use_ring = False
    if use_ring and isinstance(rb, EpisodeBuffer):
        warnings.warn(
            "buffer.device_ring=True is not supported for the episode buffer "
            "(buffer.type=episode): whole-episode storage has no fixed ring "
            "geometry to mirror; falling back to the host prefetch pipeline."
        )
        use_ring = False
    if use_ring:
        try:
            if sequence_mode:
                ring: Any = DeviceRingReplay(
                    rb,
                    device=device,
                    seed=seed,
                    sequence_overlap=int(sequence_length or 64),
                    batch_sharding=batch_sharding if world_size > 1 else None,
                )
            else:
                ring = DeviceRingTransitions(
                    rb,
                    device=device,
                    seed=seed,
                    batch_sharding=batch_sharding if world_size > 1 else None,
                )
        except ValueError as exc:
            # e.g. n_envs does not divide over the mesh's batch shards —
            # degrade to the pipelined host path instead of refusing to run
            warnings.warn(
                f"buffer.device_ring=True could not be enabled ({exc}); "
                "falling back to the host prefetch pipeline."
            )
        else:
            if lock is not None:
                ring.bind_write_lock(lock)
            return RingStaging(ring)
    # the ring paths seed the buffer's sampler at construction; the host
    # path must too, or replay draws come from OS entropy and seeded runs
    # are not reproducible (the plane's thread-vs-process bitwise gate
    # depends on this)
    if seed is not None and hasattr(rb, "seed"):
        rb.seed(int(seed))
    prefetch = bool(cfg.buffer.get("prefetch", True))
    if prefetch and bool(getattr(rb, "needs_writeback", False)):
        # TD-priority writeback must see the plan of the batch being trained
        # on; prefetching would draw burst k+1's plan before burst k's
        # priorities land, so the pipeline runs synchronous under it
        warnings.warn(
            "buffer.prefetch=True is disabled under a priority-writeback "
            "replay strategy (replay.strategy=td_priority): the post-train "
            "writeback must align with the last sampled plan."
        )
        prefetch = False
    return HostStaging(
        rb,
        batch_sharding,
        sequence_mode=sequence_mode,
        prefetch=prefetch,
        lock=lock,
    )
