"""Host-side numpy replay buffers.

Ground-up re-design of the reference data layer for TPU training. The
reference's fastest variant (v0.5.x "numpy buffers", credited in its README
benchmark table) stores rollouts as plain numpy dict-of-arrays ``[T, n_envs,
...]``; we go straight to that design (SURVEY.md preamble) because on TPU the
buffer *is* the host→HBM staging area: sampling returns numpy batches that the
prefetcher ships to device with ``jax.device_put`` double-buffering.

API parity (class and method surface mirrors the reference
``sheeprl/data/buffers.py`` + ``sheeprl/utils/memmap.py``, as pinned by its
test-suite):

- :class:`ReplayBuffer`       — uniform-sample ring buffer (buffers.py:16-216)
- :class:`SequentialReplayBuffer` — contiguous sequence sampling (buffers.py:219-339)
- :class:`EpisodeBuffer`      — whole-episode storage (buffers.py:342-525)
- :class:`EnvIndependentReplayBuffer` — per-env sub-buffers (buffers.py:528-690)

``sample_tensors``/``to_tensor`` return **jax arrays** (the reference returns
torch tensors); the optional ``device``/``sharding`` argument stages the batch
onto HBM (or a mesh sharding) directly.
"""

from __future__ import annotations

import os
import uuid
from contextlib import nullcontext
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type, Union

import numpy as np

from sheeprl_tpu.obs.dist import staleness as _staleness
from sheeprl_tpu.utils.memmap import MemmapArray, validate_memmap_mode

Arrays = Dict[str, Union[np.ndarray, MemmapArray]]


def _as_np(v: Union[np.ndarray, MemmapArray]) -> np.ndarray:
    return v.array if isinstance(v, MemmapArray) else v


def end_biased_start(rng: np.random.Generator, length: int, upper: int) -> int:
    """One end-biased window-start draw: uniform over ``[0, length)`` clamped
    to the inclusive max start ``upper``, so the probability mass of the
    clamped tail piles onto the last valid start. This IS the
    ``EpisodeBuffer`` ``prioritize_ends`` draw, factored out so the replay
    plane's ``prioritize_ends`` sampling strategy (sheeprl_tpu/replay)
    matches it bitwise by construction."""
    return min(int(rng.integers(0, length)), upper)


class ReplayBuffer:
    """Uniform-sampling ring buffer of shape ``[buffer_size, n_envs, ...]``."""

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        memmap: bool = False,
        memmap_dir: Optional[Union[str, os.PathLike]] = None,
        memmap_mode: str = "r+",
        **kwargs: Any,
    ):
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if n_envs <= 0:
            raise ValueError(f"The number of environments must be greater than zero, got: {n_envs}")
        if isinstance(obs_keys, str):
            obs_keys = (obs_keys,)
        self._buffer_size = buffer_size
        self._n_envs = n_envs
        self._obs_keys = tuple(obs_keys)
        self._memmap = memmap
        self._memmap_dir = os.fspath(memmap_dir) if memmap_dir is not None else None
        if memmap:
            validate_memmap_mode(memmap_mode)
            if self._memmap_dir is None:
                raise ValueError(
                    "The buffer is set to be memory-mapped but the 'memmap_dir' attribute is None. "
                    "Please provide a directory where to save the buffer files."
                )
            os.makedirs(self._memmap_dir, exist_ok=True)
        self._memmap_mode = memmap_mode
        self._buf: Optional[Arrays] = None
        self._pos = 0
        self._full = False
        self._rng: np.random.Generator = np.random.default_rng()
        self._write_lock: Optional[Any] = None
        # data-staleness lineage (obs/dist/staleness): per-time-row wall
        # clock of the add that wrote it, allocated lazily on the first add
        # of an instrumented run — un-instrumented runs never pay the array
        self._add_ts: Optional[np.ndarray] = None

    # -- properties -------------------------------------------------------

    @property
    def buffer(self) -> Optional[Arrays]:
        return self._buf

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def full(self) -> bool:
        return self._full

    @property
    def empty(self) -> bool:
        return self._buf is None

    @property
    def is_memmap(self) -> bool:
        return self._memmap

    def __len__(self) -> int:
        return self._buffer_size

    def seed(self, seed: Optional[int] = None) -> None:
        self._rng = np.random.default_rng(seed)

    def bind_write_lock(self, lock: Any) -> None:
        """Serialize ``add`` against a background sampler.

        The replay-staging prefetch pipeline (``data/staging.py``) samples
        burst *k+1* on a worker thread while the train program runs burst
        *k*; binding the pipeline's lock here makes every mutation take it,
        so a concurrent ``add`` can never tear a row mid-sample."""
        self._write_lock = lock

    # -- storage ----------------------------------------------------------

    def _allocate(self, data: Dict[str, np.ndarray]) -> None:
        self._buf = {}
        for k, v in data.items():
            shape = (self._buffer_size, self._n_envs) + v.shape[2:]
            if self._memmap:
                self._buf[k] = MemmapArray(
                    shape=shape,
                    dtype=v.dtype,
                    filename=os.path.join(self._memmap_dir, f"{k}.memmap"),
                    mode=self._memmap_mode,
                )
            else:
                self._buf[k] = np.empty(shape, dtype=v.dtype)

    def _validate_add(self, data: Any) -> None:
        if data is None:
            raise ValueError("The `data` replay buffer must be not None")
        if not isinstance(data, dict):
            raise ValueError(
                "`data` must be a dictionary containing Numpy arrays, "
                f"but `data` is of type `{type(data)}`"
            )
        for k, v in data.items():
            if not isinstance(v, np.ndarray):
                raise ValueError(
                    "`data` must be a dictionary containing Numpy arrays. "
                    f"Found key `{k}` of type `{type(v)}`"
                )
        last_key, last_batch_shape = None, None
        for k, v in data.items():
            if v.ndim < 2:
                raise RuntimeError(
                    "`data` must have at least 2 dimensions: [sequence_length, n_envs, ...], "
                    f"key `{k}` has shape {v.shape}"
                )
            if v.shape[1] != self._n_envs:
                raise RuntimeError(
                    f"The second dimension of `data` must equal n_envs ({self._n_envs}), "
                    f"key `{k}` has shape {v.shape}"
                )
            if last_key is not None and v.shape[:2] != last_batch_shape:
                raise RuntimeError(
                    "Every array in 'data' must be congruent in the first 2 dimensions: "
                    f"key `{k}` has shape {v.shape[:2]}, key `{last_key}` has {last_batch_shape}"
                )
            last_key, last_batch_shape = k, v.shape[:2]

    def add(self, data: Union["ReplayBuffer", Dict[str, np.ndarray]], validate_args: bool = False) -> None:
        """Insert ``[T, n_envs, ...]`` steps with ring wrap-around.

        Zero-copy contract with the async env plane (envs/vector): the slab
        views ``AsyncSharedMemVectorEnv.step`` returns are ``[n_envs, ...]``
        shared-memory blocks in exactly this layout — callers pass them
        (``data[k][np.newaxis]``) without an intermediate copy, and the
        indexed assignment below is the one copy of the whole env→ring path.
        """
        if isinstance(data, ReplayBuffer):
            data = {k: _as_np(v) for k, v in (data.buffer or {}).items()}
        if validate_args:
            self._validate_add(data)
        data = {k: np.asarray(v) for k, v in data.items()}
        first = next(iter(data.values()))
        data_len = first.shape[0]
        with self._write_lock or nullcontext():
            if self._buf is None:
                self._allocate(data)
            next_pos = (self._pos + data_len) % self._buffer_size
            # only the trailing window survives, written at the positions it
            # would have landed on had every step been inserted one by one
            write_len = min(data_len, self._buffer_size)
            start = self._pos + data_len - write_len
            idxes = np.arange(start, start + write_len) % self._buffer_size
            for k, v in data.items():
                self._buf[k][idxes] = v[-write_len:]
            ts = _staleness.take_add_stamp()
            if ts is not None:
                if self._add_ts is None:
                    self._add_ts = np.zeros(self._buffer_size, np.float64)
                self._add_ts[idxes] = ts
            if self._pos + data_len >= self._buffer_size:
                self._full = True
            self._pos = next_pos

    def advance_external(self, example_rows: Dict[str, np.ndarray], steps: int) -> None:
        """Advance the ring counters for ``steps`` time rows written OUTSIDE
        this buffer — the jitted-scan collection path writes straight into
        the device ring, and the host copy learns about it here so planning
        (``plan_transitions`` valid-window math) stays correct without the
        rows ever crossing back.

        ``example_rows`` leaves are ``[n_envs, ...]`` per-env example rows
        used to allocate storage on the first call; the host *data* is NOT
        written (the device ring owns the newest copy — see
        ``DeviceRingTransitions.sync_host``).
        """
        if steps <= 0:
            return
        with self._write_lock or nullcontext():
            if self._buf is None:
                self._allocate({k: np.asarray(v)[None] for k, v in example_rows.items()})
            ts = _staleness.take_add_stamp()
            if ts is not None:
                if self._add_ts is None:
                    self._add_ts = np.zeros(self._buffer_size, np.float64)
                write_len = min(steps, self._buffer_size)
                start = self._pos + steps - write_len
                self._add_ts[np.arange(start, start + write_len) % self._buffer_size] = ts
            if self._pos + steps >= self._buffer_size:
                self._full = True
            self._pos = (self._pos + steps) % self._buffer_size

    # -- sampling ---------------------------------------------------------

    def _valid_time_indices(self, sample_next_obs: bool) -> np.ndarray:
        if sample_next_obs:
            # the newest element has no stored successor
            if self._full:
                valid = np.arange(self._buffer_size)
                newest = (self._pos - 1) % self._buffer_size
                return np.delete(valid, newest)
            return np.arange(self._pos - 1)
        if self._full:
            return np.arange(self._buffer_size)
        return np.arange(self._pos)

    def plan_transitions(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        rng: Optional[np.random.Generator] = None,
        envs: Optional[Sequence[int]] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Draw ``batch_size * n_samples`` uniform ``(t_idx, e_idx)`` pairs —
        the single source of the valid-window semantics (no stored successor
        for the newest row under ``sample_next_obs``), shared by host sampling
        and the device-ring transition gather planner (data/device_ring.py).

        ``envs`` restricts the env draw to a subset (uniform within it) — the
        sharded ring plans each device's batch columns among the envs homed on
        that device, like the sequence ring's per-group ``pick_envs``."""
        rng = self._rng if rng is None else rng
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        if self._buf is None:
            raise ValueError("No sample has been added to the buffer")
        if not self._full and self._pos == 0:
            raise ValueError("No sample has been added to the buffer")
        valid = self._valid_time_indices(sample_next_obs)
        if len(valid) == 0:
            if not self._full:
                raise RuntimeError(
                    "You want to sample the next observations, but only one sample has been "
                    "added to the buffer. Make sure that at least two samples are added."
                )
            raise ValueError("No valid sample index to draw from")
        total = batch_size * n_samples
        t_idx = valid[rng.integers(0, len(valid), size=total)]
        if envs is None:
            e_idx = rng.integers(0, self._n_envs, size=total)
        else:
            envs_arr = np.asarray(envs, dtype=np.int64)
            e_idx = envs_arr[rng.integers(0, len(envs_arr), size=total)]
        self._observe_sample_ages(t_idx)
        return t_idx, e_idx

    def valid_time_indices(self, sample_next_obs: bool = False) -> np.ndarray:
        """Public view of the sampleable time window (ring positions) — the
        replay plane's sampling strategies draw over exactly this set so the
        no-stored-successor rule stays defined in one place."""
        return self._valid_time_indices(sample_next_obs)

    def age_ordered_time_indices(self, sample_next_obs: bool = False) -> np.ndarray:
        """The sampleable time window ordered oldest→newest (insertion
        order). A flat ring is age-ordered ``0..pos-1`` until it wraps;
        once full, age order starts at the write head ``_pos`` (the oldest
        surviving row) and walks the ring. The ``prioritize_ends`` strategy
        generalizes the EpisodeBuffer end bias over this ordering."""
        if self._full:
            ordered = (self._pos + np.arange(self._buffer_size)) % self._buffer_size
        else:
            ordered = np.arange(self._pos)
        if sample_next_obs and len(ordered):
            ordered = ordered[:-1]  # the newest row has no stored successor
        return ordered

    def observe_sample_ages(self, t_idx: np.ndarray) -> None:
        """Staleness chokepoint for EXTERNAL planners (the replay plane's
        strategies): any plan that bypasses ``plan_transitions`` must feed
        its drawn rows through here to keep the PR-9 lineage intact."""
        self._observe_sample_ages(t_idx)

    def gather_plan(
        self,
        t_idx: np.ndarray,
        e_idx: np.ndarray,
        sample_next_obs: bool = False,
        clone: bool = False,
    ) -> Dict[str, np.ndarray]:
        """Gather a planned ``(t_idx, e_idx)`` index set as flat ``[total,
        ...]`` rows — the entry the cross-shard sampler uses after planning
        each shard's slice of a burst (sheeprl_tpu/replay/sharded.py)."""
        if self._buf is None:
            raise ValueError("No sample has been added to the buffer")
        t_idx = np.asarray(t_idx, dtype=np.int64).reshape(-1)
        e_idx = np.asarray(e_idx, dtype=np.int64).reshape(-1)
        return self._gather(t_idx, e_idx, sample_next_obs, clone)

    def _observe_sample_ages(self, t_idx: np.ndarray) -> None:
        """Feed the drawn rows' ages into the staleness histogram — one
        chokepoint under host sampling AND the device-ring planners (both
        route their index plans through plan_transitions/plan_starts)."""
        if self._add_ts is not None and _staleness.installed() is not None:
            import time

            stamps = self._add_ts[t_idx]
            # rows that predate instrumentation (a resumed buffer snapshot)
            # carry stamp 0 — their "age" would be the unix epoch
            stamps = stamps[stamps > 0.0]
            if stamps.size:
                _staleness.observe_sample_ages(time.time() - stamps)

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        """Uniformly sample ``[n_samples, batch_size, ...]`` transitions."""
        t_idx, e_idx = self.plan_transitions(batch_size, sample_next_obs, n_samples)
        out = self._gather(t_idx, e_idx, sample_next_obs, clone)
        return {k: v.reshape(n_samples, batch_size, *v.shape[1:]) for k, v in out.items()}

    def _get_samples(self, idxes: np.ndarray, sample_next_obs: bool = False) -> Dict[str, np.ndarray]:
        if self._buf is None:
            raise RuntimeError(
                "The buffer has not been initialized. Try to add some data first."
            )
        idxes = np.asarray(idxes, dtype=np.int64).reshape(-1)
        e_idx = self._rng.integers(0, self._n_envs, size=len(idxes))
        return self._gather(idxes, e_idx, sample_next_obs, clone=False)

    def _gather(
        self, t_idx: np.ndarray, e_idx: np.ndarray, sample_next_obs: bool, clone: bool
    ) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        for k, v in self._buf.items():
            arr = _as_np(v)
            sel = arr[t_idx, e_idx]
            out[k] = np.array(sel) if clone else sel
            if sample_next_obs and k in self._obs_keys:
                nxt = arr[(t_idx + 1) % self._buffer_size, e_idx]
                out[f"next_{k}"] = np.array(nxt) if clone else nxt
        return out

    # -- jax staging ------------------------------------------------------

    def sample_tensors(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        dtype: Optional[Any] = None,
        device: Optional[Any] = None,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        """Like :meth:`sample` but stages the batch to device as jax arrays."""
        batch = self.sample(batch_size, sample_next_obs, clone, n_samples, **kwargs)
        return to_device(batch, dtype=dtype, device=device)

    def to_tensor(
        self, dtype: Optional[Any] = None, clone: bool = False, device: Optional[Any] = None
    ) -> Dict[str, Any]:
        if self._buf is None:
            raise RuntimeError("The buffer has not been initialized. Try to add some data first.")
        return to_device({k: _as_np(v) for k, v in self._buf.items()}, dtype=dtype, device=device)

    # -- dict access ------------------------------------------------------

    def __getitem__(self, key: str) -> np.ndarray:
        if not isinstance(key, str):
            raise TypeError("'key' must be a string")
        if self._buf is None:
            raise RuntimeError("The buffer has not been initialized. Try to add some data first.")
        return self._buf[key]

    def __setitem__(self, key: str, value: Union[np.ndarray, MemmapArray]) -> None:
        if self._buf is None:
            raise RuntimeError("The buffer has not been initialized. Try to add some data first.")
        value_np = _as_np(value)
        if value_np.shape[:2] != (self._buffer_size, self._n_envs):
            raise RuntimeError(
                f"'value' must have shape [{self._buffer_size}, {self._n_envs}, ...], got {value_np.shape}"
            )
        if self._memmap:
            old = self._buf.get(key)
            if isinstance(old, MemmapArray):
                if old.shape == value_np.shape and old.dtype == value_np.dtype:
                    old.array = value_np  # write in place, keep the backing file
                    return
                # close+unlink the old mapping *before* re-creating the same path,
                # else the old owner's __del__ would unlink the new backing file
                old.__del__()
                self._buf.pop(key, None)
            self._buf[key] = MemmapArray.from_array(
                value_np,
                filename=os.path.join(self._memmap_dir, f"{key}.memmap"),
                mode=self._memmap_mode,
            )
        else:
            self._buf[key] = np.array(value_np)

    def __contains__(self, key: str) -> bool:
        return self._buf is not None and key in self._buf

    # -- checkpointing ----------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        return {
            "buffer": {k: np.array(_as_np(v)) for k, v in (self._buf or {}).items()},
            "pos": self._pos,
            "full": self._full,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        buf = state["buffer"]
        if buf:
            if self._buf is None:
                self._allocate(buf)  # stored arrays are already [size, n_envs, ...]
            for k, v in buf.items():
                self._buf[k][...] = v
        self._pos = int(state["pos"])
        self._full = bool(state["full"])


class SequentialReplayBuffer(ReplayBuffer):
    """Ring buffer sampling *contiguous* sequences ``[n_samples, seq_len, batch, ...]``.

    Valid sequence starts never straddle the write head ``_pos`` (reference
    buffers.py:312-339); when the buffer is full, sequences may wrap around
    the end of storage.
    """

    def plan_starts(
        self,
        total: int,
        sequence_length: int,
        effective_len: Optional[int] = None,
        rng: Optional[np.random.Generator] = None,
    ) -> np.ndarray:
        """Draw ``total`` valid sequence start indices — the single source of
        the never-straddle-the-write-head semantics, shared by host sampling
        and the device-ring gather planner (data/device_ring.py)."""
        rng = self._rng if rng is None else rng
        effective_len = sequence_length if effective_len is None else effective_len
        if self._full:
            max_offset = self._buffer_size - effective_len
            if max_offset < 0:
                raise ValueError(
                    f"Cannot sample a sequence of length {sequence_length} from a buffer of size "
                    f"{self._buffer_size}"
                )
            offsets = rng.integers(0, max_offset + 1, size=total)
            starts = (self._pos + offsets) % self._buffer_size
            self._observe_sample_ages(starts)
            return starts
        max_start = self._pos - effective_len
        if max_start < 0:
            raise ValueError(
                f"Cannot sample a sequence of length {sequence_length}: the buffer only "
                f"contains {self._pos} steps"
            )
        starts = rng.integers(0, max_start + 1, size=total)
        self._observe_sample_ages(starts)
        return starts

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        sequence_length: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        if sequence_length <= 0:
            raise ValueError(f"'sequence_length' ({sequence_length}) must be greater than 0")
        if self._buf is None or (not self._full and self._pos == 0):
            raise ValueError("No sample has been added to the buffer")
        effective_len = sequence_length + (1 if sample_next_obs else 0)
        total = batch_size * n_samples
        starts = self.plan_starts(total, sequence_length, effective_len)
        e_idx = self._rng.integers(0, self._n_envs, size=total)
        # [total, seq_len] absolute time indices (wrap-around safe)
        seq = (starts[:, None] + np.arange(sequence_length)[None, :]) % self._buffer_size
        out: Dict[str, np.ndarray] = {}
        for k, v in self._buf.items():
            arr = _as_np(v)
            sel = arr[seq, e_idx[:, None]]  # [total, seq_len, ...]
            sel = sel.reshape(n_samples, batch_size, sequence_length, *sel.shape[2:])
            out[k] = np.array(sel.swapaxes(1, 2)) if clone else sel.swapaxes(1, 2)
            if sample_next_obs and k in self._obs_keys:
                nseq = (seq + 1) % self._buffer_size
                nsel = arr[nseq, e_idx[:, None]].reshape(n_samples, batch_size, sequence_length, *sel.shape[3:])
                out[f"next_{k}"] = np.array(nsel.swapaxes(1, 2)) if clone else nsel.swapaxes(1, 2)
        return out


class EpisodeBuffer:
    """Whole-episode storage with invariants (reference buffers.py:342-525).

    Episodes are closed by ``dones`` flags; only episodes of length in
    ``[sequence_length, buffer_size]`` are kept, FIFO-evicted by cumulative
    step count. Sampling returns ``[n_samples, sequence_length, batch, ...]``
    windows, optionally biased toward episode ends (``prioritize_ends``).
    """

    def __init__(
        self,
        buffer_size: int,
        sequence_length: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        prioritize_ends: bool = False,
        memmap: bool = False,
        memmap_dir: Optional[Union[str, os.PathLike]] = None,
        memmap_mode: str = "r+",
    ):
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if sequence_length <= 0:
            raise ValueError(f"The sequence length must be greater than zero, got: {sequence_length}")
        if buffer_size < sequence_length:
            raise ValueError(
                f"The sequence length must be lower than the buffer size, got: bs = {buffer_size}"
                f" and sl = {sequence_length}"
            )
        if isinstance(obs_keys, str):
            obs_keys = (obs_keys,)
        self._buffer_size = buffer_size
        self._sequence_length = sequence_length
        self._n_envs = n_envs
        self._obs_keys = tuple(obs_keys)
        self._prioritize_ends = prioritize_ends
        self._memmap = memmap
        self._memmap_dir = os.fspath(memmap_dir) if memmap_dir is not None else None
        if memmap:
            validate_memmap_mode(memmap_mode)
            if self._memmap_dir is None:
                raise ValueError(
                    "The buffer is set to be memory-mapped but the 'memmap_dir' attribute is None. "
                    "Please provide a directory where to save the buffer files."
                )
            os.makedirs(self._memmap_dir, exist_ok=True)
        self._memmap_mode = memmap_mode
        self._buf: List[Arrays] = []
        self._open_episodes: List[List[Dict[str, np.ndarray]]] = [[] for _ in range(n_envs)]
        self._cum_steps = 0  # running step count; kept in sync by save/evict
        self._rng: np.random.Generator = np.random.default_rng()
        self._write_lock: Optional[Any] = None

    # -- properties -------------------------------------------------------

    @property
    def buffer(self) -> List[Arrays]:
        return self._buf

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def sequence_length(self) -> int:
        return self._sequence_length

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def prioritize_ends(self) -> bool:
        return self._prioritize_ends

    @property
    def is_memmap(self) -> bool:
        return self._memmap

    @property
    def _cum_length(self) -> int:
        return self._cum_steps

    @property
    def full(self) -> bool:
        return self._buffer_size - self._cum_steps < self._sequence_length

    def __len__(self) -> int:
        return len(self._buf)

    def seed(self, seed: Optional[int] = None) -> None:
        self._rng = np.random.default_rng(seed)

    def bind_write_lock(self, lock: Any) -> None:
        """Serialize ``add`` against a background sampler (see ReplayBuffer)."""
        self._write_lock = lock

    # -- insertion --------------------------------------------------------

    def _validate_data(self, data: Any) -> None:
        if data is None:
            raise ValueError("The `data` replay buffer must be not None")
        if not isinstance(data, dict):
            raise ValueError(
                "`data` must be a dictionary containing Numpy arrays, "
                f"but `data` is of type `{type(data)}`"
            )
        for k, v in data.items():
            if not isinstance(v, np.ndarray):
                raise ValueError(
                    f"`data` must be a dictionary containing Numpy arrays. Found key `{k}` "
                    f"of type `{type(v)}`"
                )
        last_key, last_shape = None, None
        for k, v in data.items():
            if v.ndim < 2:
                raise RuntimeError(
                    "`data` must have at least 2: [sequence_length, n_envs, ...], "
                    f"key `{k}` has shape {v.shape}"
                )
            if last_key is not None and v.shape[:2] != last_shape:
                raise RuntimeError(
                    "Every array in `data` must be congruent in the first 2 dimensions: "
                    f"key `{k}` has shape {v.shape[:2]}, key `{last_key}` has {last_shape}"
                )
            last_key, last_shape = k, v.shape[:2]
        if "dones" not in data:
            raise RuntimeError(f"The episode must contain the `dones` key, got: {set(data.keys())}")

    def add(
        self,
        data: Union[Dict[str, np.ndarray], "ReplayBuffer"],
        env_idxes: Optional[Sequence[int]] = None,
        validate_args: bool = False,
    ) -> None:
        if isinstance(data, ReplayBuffer):
            data = {k: np.array(_as_np(v)) for k, v in (data.buffer or {}).items()}
        if validate_args:
            self._validate_data(data)
        n_cols = next(iter(data.values())).shape[1] if isinstance(data, dict) and data else 0
        if env_idxes is None:
            env_idxes = list(range(n_cols))
        if validate_args:
            for idx in env_idxes:
                if idx < 0 or idx >= self._n_envs:
                    raise ValueError(
                        f"The indices of the environment must be integers in [0, {self._n_envs}), "
                        f"given {idx}"
                    )
            if n_cols != len(env_idxes):
                raise RuntimeError(
                    f"`data` has {n_cols} env columns but {len(env_idxes)} env indices were given"
                )
        with self._write_lock or nullcontext():
            for col, env in enumerate(env_idxes):
                chunk = {k: np.asarray(v)[:, col] for k, v in data.items()}
                self._add_env_chunk(chunk, env)

    def _add_env_chunk(self, chunk: Dict[str, np.ndarray], env: int) -> None:
        dones = chunk["dones"].reshape(len(chunk["dones"]), -1)[:, 0]
        start = 0
        for t in np.flatnonzero(dones > 0):
            piece = {k: v[start : t + 1] for k, v in chunk.items()}
            self._open_episodes[env].append(piece)
            start = t + 1
            self._close_episode(env)
        if start < len(dones):
            self._open_episodes[env].append({k: v[start:] for k, v in chunk.items()})

    def _close_episode(self, env: int) -> None:
        chunks = self._open_episodes[env]
        self._open_episodes[env] = []
        if not chunks:
            return
        length = sum(len(c["dones"]) for c in chunks)
        if length >= self._sequence_length:
            self.save_episode(chunks)

    def save_episode(self, episode_chunks: Union[Dict[str, np.ndarray], List[Dict[str, np.ndarray]]]) -> None:
        """Validate and persist one finished episode (list of chunks or a dict)."""
        if isinstance(episode_chunks, dict):
            episode_chunks = [episode_chunks]
        if len(episode_chunks) == 0:
            raise RuntimeError("The episode must contain at least one step")
        episode = {
            k: np.concatenate([np.asarray(c[k]) for c in episode_chunks], axis=0)
            for k in episode_chunks[0].keys()
        }
        dones = episode["dones"].reshape(len(episode["dones"]), -1)[:, 0]
        if dones.sum() != 1:
            raise RuntimeError(f"The episode must contain exactly one done, got: {int(dones.sum())}")
        if dones[-1] != 1:
            raise RuntimeError("The last step must contain a done, got: 0")
        ep_len = len(dones)
        if ep_len < self._sequence_length or ep_len > self._buffer_size:
            raise RuntimeError(
                f"Invalid episode length: the episode length ({ep_len}) must be at least "
                f"sequence_length ({self._sequence_length}) and at most buffer_size ({self._buffer_size})"
            )
        # FIFO eviction by cumulative step count
        while self._cum_steps + ep_len > self._buffer_size and self._buf:
            self._evict_oldest()
        if self._memmap:
            ep_dir = os.path.join(self._memmap_dir, f"episode_{uuid.uuid4().hex}")
            episode = {
                k: MemmapArray.from_array(
                    v, filename=os.path.join(ep_dir, f"{k}.memmap"), mode=self._memmap_mode
                )
                for k, v in episode.items()
            }
        self._buf.append(episode)
        self._cum_steps += ep_len

    def _evict_oldest(self) -> None:
        old = self._buf.pop(0)
        self._cum_steps -= len(_as_np(old["dones"]))
        # unlink memmap files now and remove the per-episode directory
        dirs = {os.path.dirname(v.filename) for v in old.values() if isinstance(v, MemmapArray)}
        for v in old.values():
            if isinstance(v, MemmapArray):
                v.__del__()
        old.clear()
        for d in dirs:
            try:
                os.rmdir(d)
            except OSError:
                pass

    # -- sampling ---------------------------------------------------------

    def sample(
        self,
        batch_size: int,
        n_samples: int = 1,
        clone: bool = False,
        sample_next_obs: bool = False,
        prioritize_ends: Optional[bool] = None,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        if len(self._buf) == 0:
            raise ValueError("No sample has been added to the buffer")
        if prioritize_ends is None:
            prioritize_ends = self._prioritize_ends
        sl = self._sequence_length
        effective = sl + (1 if sample_next_obs else 0)
        lengths = np.array([len(_as_np(ep["dones"])) for ep in self._buf])
        eligible = np.flatnonzero(lengths >= effective)
        if len(eligible) == 0:
            raise ValueError(f"No episode long enough to sample sequences of length {sl}")
        total = batch_size * n_samples
        chosen = eligible[self._rng.integers(0, len(eligible), size=total)]
        out: Dict[str, List[np.ndarray]] = {}
        for i in chosen:
            ep = self._buf[i]
            ep_len = lengths[i]
            upper = ep_len - effective  # inclusive max start
            if prioritize_ends:
                start = end_biased_start(self._rng, int(ep_len), int(upper))
            else:
                start = int(self._rng.integers(0, upper + 1))
            for k in ep.keys():
                arr = _as_np(ep[k])
                out.setdefault(k, []).append(arr[start : start + sl])
                if sample_next_obs and k in self._obs_keys:
                    out.setdefault(f"next_{k}", []).append(arr[start + 1 : start + sl + 1])
        stacked = {}
        for k, vs in out.items():
            arr = np.stack(vs, axis=0).reshape(n_samples, batch_size, sl, *vs[0].shape[1:])
            arr = arr.swapaxes(1, 2)  # [n_samples, sl, batch, ...]
            stacked[k] = np.array(arr) if clone else arr
        return stacked

    def sample_tensors(
        self,
        batch_size: int,
        n_samples: int = 1,
        clone: bool = False,
        sample_next_obs: bool = False,
        prioritize_ends: Optional[bool] = None,
        dtype: Optional[Any] = None,
        device: Optional[Any] = None,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        batch = self.sample(batch_size, n_samples, clone, sample_next_obs, prioritize_ends, **kwargs)
        return to_device(batch, dtype=dtype, device=device)

    # -- checkpointing ----------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        return {
            "buffer": [{k: np.array(_as_np(v)) for k, v in ep.items()} for ep in self._buf],
            "open_episodes": [
                [{k: np.array(v) for k, v in chunk.items()} for chunk in env_chunks]
                for env_chunks in self._open_episodes
            ],
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._buf = []
        for ep in state["buffer"]:
            if self._memmap:
                ep_dir = os.path.join(self._memmap_dir, f"episode_{uuid.uuid4().hex}")
                ep = {
                    k: MemmapArray.from_array(
                        v, filename=os.path.join(ep_dir, f"{k}.memmap"), mode=self._memmap_mode
                    )
                    for k, v in ep.items()
                }
            self._buf.append(ep)
        self._cum_steps = sum(len(_as_np(ep["dones"])) for ep in self._buf)
        self._open_episodes = [
            [{k: np.array(v) for k, v in chunk.items()} for chunk in env_chunks]
            for env_chunks in state.get("open_episodes", [[] for _ in range(self._n_envs)])
        ]


class EnvIndependentReplayBuffer:
    """One sub-buffer per environment (reference buffers.py:528-690).

    Keeps vectorized envs with unaligned episode phases temporally coherent:
    ``add(data, env_idxes)`` routes columns to specific env buffers, sampling
    draws a balanced mix across envs that hold data.
    """

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        memmap: bool = False,
        memmap_dir: Optional[Union[str, os.PathLike]] = None,
        memmap_mode: str = "r+",
        buffer_cls: Type[ReplayBuffer] = ReplayBuffer,
        **kwargs: Any,
    ):
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if n_envs <= 0:
            raise ValueError(f"The number of environments must be greater than zero, got: {n_envs}")
        if memmap:
            validate_memmap_mode(memmap_mode)
            if memmap_dir is None:
                raise ValueError(
                    "The buffer is set to be memory-mapped but the 'memmap_dir' attribute is None. "
                    "Please provide a directory where to save the buffer files."
                )
        self._buffer_size = buffer_size
        self._n_envs = n_envs
        self._buffer_cls = buffer_cls
        self._concat_along_axis = 2 if issubclass(buffer_cls, SequentialReplayBuffer) else 1
        self._rng: np.random.Generator = np.random.default_rng()
        self._write_lock: Optional[Any] = None
        self._buf: List[ReplayBuffer] = [
            buffer_cls(
                buffer_size,
                n_envs=1,
                obs_keys=obs_keys,
                memmap=memmap,
                memmap_dir=os.path.join(os.fspath(memmap_dir), f"env_{i}") if memmap_dir else None,
                memmap_mode=memmap_mode,
                **kwargs,
            )
            for i in range(n_envs)
        ]

    @property
    def buffer(self) -> List[ReplayBuffer]:
        return self._buf

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def is_memmap(self) -> bool:
        return all(b.is_memmap for b in self._buf)

    @property
    def full(self) -> bool:
        return all(b.full for b in self._buf)

    def seed(self, seed: Optional[int] = None) -> None:
        # the wrapper's own rng drives pick_envs (the per-batch env mix) and
        # must be reseeded along with the sub-buffers, or seeded runs still
        # draw their env partitions from OS entropy (offset past the
        # sub-buffer streams so no two generators share a seed)
        self._rng = np.random.default_rng(None if seed is None else seed + self._n_envs)
        for i, b in enumerate(self._buf):
            b.seed(None if seed is None else seed + i)

    def bind_write_lock(self, lock: Any) -> None:
        """Serialize ``add`` against a background sampler (see ReplayBuffer)."""
        self._write_lock = lock

    def add(
        self,
        data: Dict[str, np.ndarray],
        env_idxes: Optional[Sequence[int]] = None,
        validate_args: bool = False,
    ) -> None:
        n_cols = next(iter(data.values())).shape[1]
        if env_idxes is None:
            env_idxes = list(range(self._n_envs))
        if n_cols != len(env_idxes):
            raise ValueError(
                f"Cannot add data with {n_cols} env columns to {len(env_idxes)} env indices"
            )
        for idx in env_idxes:
            if idx < 0 or idx >= self._n_envs:
                raise ValueError(
                    f"The indices of the environment must be integers in [0, {self._n_envs}), given {idx}"
                )
        with self._write_lock or nullcontext():
            for col, env in enumerate(env_idxes):
                self._buf[env].add(
                    {k: np.asarray(v)[:, col : col + 1] for k, v in data.items()},
                    validate_args=validate_args,
                )

    def pick_envs(
        self,
        batch_size: int,
        rng: Optional[np.random.Generator] = None,
        envs: Optional[Sequence[int]] = None,
    ) -> Tuple[List[int], np.ndarray]:
        """Balanced env mix over the sub-buffers that hold data — shared by
        host sampling and the device-ring gather planner (which restricts
        ``envs`` to one mesh shard's group so the eligibility rule lives in
        exactly one place)."""
        rng = self._rng if rng is None else rng
        candidates = range(len(self._buf)) if envs is None else envs
        with_data = [
            int(i) for i in candidates
            if not self._buf[i].empty and (self._buf[i].full or self._buf[i]._pos > 0)
        ]
        if not with_data:
            raise ValueError(
                "No sample has been added to the buffer"
                if envs is None
                else f"No sample has been added to any of envs {list(envs)}"
            )
        picks = rng.integers(0, len(with_data), size=batch_size)
        return with_data, np.bincount(picks, minlength=len(with_data))

    def sample(self, batch_size: int, n_samples: int = 1, **kwargs: Any) -> Dict[str, np.ndarray]:
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        with_data, counts = self.pick_envs(batch_size)
        parts = []
        for j, env in enumerate(with_data):
            if counts[j] == 0:
                continue
            parts.append(self._buf[env].sample(int(counts[j]), n_samples=n_samples, **kwargs))
        keys = parts[0].keys()
        return {k: np.concatenate([p[k] for p in parts], axis=self._concat_along_axis) for k in keys}

    def sample_tensors(
        self,
        batch_size: int,
        n_samples: int = 1,
        dtype: Optional[Any] = None,
        device: Optional[Any] = None,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        batch = self.sample(batch_size, n_samples=n_samples, **kwargs)
        return to_device(batch, dtype=dtype, device=device)

    def state_dict(self) -> Dict[str, Any]:
        return {"buffers": [b.state_dict() for b in self._buf]}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        for b, s in zip(self._buf, state["buffers"]):
            b.load_state_dict(s)


def to_device(batch: Dict[str, np.ndarray], dtype: Optional[Any] = None, device: Optional[Any] = None):
    """Stage a numpy batch onto a jax device (or sharding) as one transfer.

    This is a host→HBM staging chokepoint: the run telemetry counts the bytes
    shipped and times the dispatch under the ``stage_h2d`` phase span (both
    no-ops when ``metric.telemetry`` is disabled).
    """
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.obs.counters import count_h2d
    from sheeprl_tpu.obs.spans import span

    with span("Time/stage_h2d_time", phase="stage_h2d"):
        out = {}
        for k, v in batch.items():
            arr = jnp.asarray(v, dtype=dtype) if device is None else jax.device_put(
                v.astype(dtype) if dtype is not None else v, device
            )
            out[k] = arr
    count_h2d(batch)
    return out
