"""Parallelism toolkit: named meshes, sequence/context parallelism.

Data parallelism (the reference's DDP world, SURVEY §2.8) lives in
:class:`sheeprl_tpu.fabric.Fabric`; this package holds the mesh construction
shared by everything and the long-context primitives (ring attention,
Ulysses all-to-all) that go beyond the reference's feature surface.
"""

from sheeprl_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    axis_size,
    make_mesh,
    pad_to_multiple,
    shard_batch_and_sequence,
    sharding,
)
from sheeprl_tpu.parallel.ring import (
    attention,
    ring_attention,
    ring_self_attention,
    ulysses_attention,
)
from sheeprl_tpu.parallel.shard import (
    DEFAULT_MIN_SHARD_BYTES,
    ShardingPlan,
    assign_spec,
    make_plan,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "SEQ_AXIS",
    "DEFAULT_MIN_SHARD_BYTES",
    "ShardingPlan",
    "assign_spec",
    "axis_size",
    "make_mesh",
    "make_plan",
    "pad_to_multiple",
    "shard_batch_and_sequence",
    "sharding",
    "attention",
    "ring_attention",
    "ring_self_attention",
    "ulysses_attention",
]
