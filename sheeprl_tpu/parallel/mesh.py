"""Multi-axis device mesh construction and sharding helpers.

The reference's only parallelism is DDP data-parallel over process ranks
(SURVEY §2.8); its comm backend is NCCL/Gloo through torch.distributed. Here
the distributed substrate is a named :class:`jax.sharding.Mesh` and XLA
collectives over ICI/DCN, and this module is the one place that builds
meshes — the runtime (:class:`sheeprl_tpu.fabric.Fabric`) uses a 1-D
``('data',)`` mesh, while long-sequence workloads can ask for an extra
``'seq'`` (context-parallel) axis and expert/tensor axes are available for
headroom beyond the reference's feature surface.

TPU notes: ``jax.experimental.mesh_utils.create_device_mesh`` lays the mesh
out so that neighboring mesh coordinates are ICI neighbors, which is what
makes ``ppermute`` rings (ring attention, §ring.py) ride ICI at full
bisection bandwidth instead of hopping through DCN.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis names, in mesh-major order. data = batch DP (the reference's
# DDP world), seq = sequence/context parallelism (ring attention / Ulysses),
# model = tensor parallelism headroom.
DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"


def make_mesh(
    axes: Dict[str, int],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named mesh with the given ``{axis_name: size}`` layout.

    Any single axis may be ``-1`` to absorb the remaining devices. The
    product of axis sizes must equal the device count. Uses
    ``mesh_utils.create_device_mesh`` when the devices are one homogeneous
    slice (ICI-aware layout); falls back to a reshape otherwise (CPU test
    meshes).
    """
    devs: List[jax.Device] = list(devices) if devices is not None else list(jax.devices())
    names = tuple(axes.keys())
    sizes = list(axes.values())
    wild = [i for i, s in enumerate(sizes) if s == -1]
    if len(wild) > 1:
        raise ValueError(f"At most one mesh axis may be -1, got {axes}")
    fixed = int(np.prod([s for s in sizes if s != -1]))
    if wild:
        if len(devs) % fixed != 0:
            raise ValueError(f"{len(devs)} devices do not divide mesh {axes}")
        sizes[wild[0]] = len(devs) // fixed
    if int(np.prod(sizes)) != len(devs):
        raise ValueError(f"Mesh {dict(zip(names, sizes))} needs {int(np.prod(sizes))} devices, have {len(devs)}")
    if devs[0].platform == "cpu":
        # Virtual CPU test meshes have no interconnect topology to optimize
        # (and create_device_mesh rejects some host-device layouts).
        dev_array = np.asarray(devs).reshape(tuple(sizes))
    else:
        # Accelerators: let mesh_utils lay the mesh out so neighboring mesh
        # coordinates are ICI neighbors. A failure here means the requested
        # topology is genuinely wrong — a silent reshape fallback would put
        # ppermute rings on DCN and quietly collapse throughput, so raise.
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(tuple(sizes), devices=devs)
    return Mesh(dev_array, names)


def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis] if axis in mesh.shape else 1


def sharding(mesh: Mesh, *spec: Any) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def shard_batch_and_sequence(
    mesh: Mesh,
    batch_axis: Optional[str] = DATA_AXIS,
    seq_axis: Optional[str] = SEQ_AXIS,
) -> NamedSharding:
    """Sharding for a ``[B, T, ...]`` activation: B over data, T over seq."""
    b = batch_axis if batch_axis in mesh.shape else None
    t = seq_axis if seq_axis in mesh.shape else None
    return NamedSharding(mesh, P(b, t))


def pad_to_multiple(x, multiple: int, axis: int) -> Tuple[Any, int]:
    """Right-pad ``axis`` to a multiple (sequence sharding needs equal local
    blocks). Returns the padded array and the pad amount. Works on numpy and
    jax arrays (incl. tracers, so it can be called under ``jit``)."""
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x, 0
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    xp = np if isinstance(x, np.ndarray) else jnp
    return xp.pad(x, widths), pad
