"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no attention and therefore no sequence parallelism
(SURVEY §5.7) — its longest "sequence" is a replay window scanned by a GRU.
For the TPU framework long-context support is first-class: these primitives
shard the *sequence* dimension of attention over a named mesh axis so
contexts far beyond one chip's HBM can be trained.

Two standard schemes, both built on XLA collectives (no NCCL):

- :func:`ring_attention` — blockwise flash-style attention where K/V blocks
  rotate around the mesh axis with ``lax.ppermute`` (one ICI hop per step)
  while each device keeps a running (max, denominator, numerator) softmax
  accumulator. Memory per device is O(T/P); communication is P−1 neighbor
  exchanges fully overlappable with the block matmuls. (Liu et al., "Ring
  Attention with Blockwise Transformers".)
- :func:`ulysses_attention` — all-to-all resharding: sequence-sharded
  Q/K/V are transposed to *head*-sharded with one ``lax.all_to_all``, plain
  local attention runs over the full sequence, and a second all-to-all
  restores sequence sharding. Cheaper collectives for moderate T, requires
  num_heads divisible by the axis size. (DeepSpeed-Ulysses.)

Both are pure jax functions meant to run *inside* ``jax.shard_map`` (the
caller owns the mesh); :func:`ring_self_attention` is the convenience
wrapper that does the shard_map plumbing from a global ``[B, T, H, D]``.
All paths are differentiable (ppermute/all_to_all have transposes), so they
drop into training steps, not just inference.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from sheeprl_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS
from sheeprl_tpu.utils.jax_compat import axis_size, shard_map

_NEG_INF = -1e30


def _block_scores(q: jnp.ndarray, k: jnp.ndarray, scale: float) -> jnp.ndarray:
    # q: [B, Tq, H, D], k: [B, Tk, H, D] -> [B, H, Tq, Tk]
    return jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale


def _causal_mask(q_start: jnp.ndarray, k_start: jnp.ndarray, tq: int, tk: int) -> jnp.ndarray:
    qpos = q_start + jnp.arange(tq)
    kpos = k_start + jnp.arange(tk)
    return qpos[:, None] >= kpos[None, :]  # [Tq, Tk]


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Plain single-device softmax attention over ``[B, T, H, D]`` — the
    numerical reference for the parallel schemes and the local kernel of
    :func:`ulysses_attention`."""
    scale = float(q.shape[-1]) ** -0.5 if scale is None else scale
    scores = _block_scores(q, k, scale)
    if causal:
        mask = _causal_mask(jnp.int32(0), jnp.int32(0), q.shape[1], k.shape[1])
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = SEQ_AXIS,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Blockwise ring attention over the ``axis_name`` mesh axis.

    Call inside ``shard_map`` with the sequence dim sharded: ``q``/``k``/``v``
    are the *local* blocks ``[B, T_local, H, D]`` of a global ``[B, T, H, D]``.
    Returns the local output block. K/V travel the ring; Q stays put.

    Known trade-off: with ``causal=True`` and contiguous block assignment,
    devices holding early blocks compute fully-masked score/PV matmuls on
    ~half the ring steps (SPMD runs the same program everywhere, so the work
    cannot be branched away). A zigzag/striped block assignment would balance
    this; at the ring sizes the framework targets (≤ one pod slice) the
    imbalance is bounded by 2× on the attention FLOPs only.
    """
    p = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    scale = float(q.shape[-1]) ** -0.5 if scale is None else scale
    b, tq, h, d = q.shape
    tk = k.shape[1]

    # Running flash accumulators: numerator [B,Tq,H,D], max & denom [B,H,Tq].
    # Derive them from q (×0) so they inherit q's device-varying type over
    # every mesh axis (shard_map vma typing).
    zero_q = (q * 0).astype(jnp.float32)
    acc = zero_q
    m = jnp.einsum("bqhd->bhq", zero_q) + _NEG_INF
    l = jnp.einsum("bqhd->bhq", zero_q)
    # Send to the right neighbor; after s steps we hold block (my − s) mod P.
    perm = [(i, (i + 1) % p) for i in range(p)]

    # Unrolled over the (static, small) ring size so the last iteration can
    # skip the K/V exchange — P−1 ICI hops, not P. (Inside a scan the
    # ppermute is a collective and XLA cannot dead-code the wasted one.)
    kb, vb = k, v
    for s in range(p):
        scores = _block_scores(q, kb.astype(q.dtype), scale)  # [B,H,Tq,Tk]
        if causal:
            kv_block = (my - s) % p
            mask = _causal_mask(my * tq, kv_block * tk, tq, tk)
            scores = jnp.where(mask[None, None], scores, _NEG_INF)
        scores = scores.astype(jnp.float32)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        # Guard exp(-inf - -inf): rows with no unmasked key yet keep m=-inf.
        alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
        probs = jnp.exp(scores - m_new[..., None])
        if causal:
            probs = jnp.where(mask[None, None], probs, 0.0)
        l = l * alpha + probs.sum(axis=-1)
        acc = acc * jnp.einsum("bhq->bqh", alpha)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", probs, vb.astype(jnp.float32)
        )
        m = m_new
        if s + 1 < p:
            kb = jax.lax.ppermute(kb, axis_name, perm)
            vb = jax.lax.ppermute(vb, axis_name, perm)
    denom = jnp.einsum("bhq->bqh", l)[..., None]
    return (acc / jnp.maximum(denom, 1e-30)).astype(q.dtype)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = SEQ_AXIS,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """All-to-all (Ulysses) sequence parallelism over ``axis_name``.

    Inside ``shard_map``: local blocks ``[B, T_local, H, D]`` with ``H``
    divisible by the axis size. One all-to-all turns sequence sharding into
    head sharding (full T on every device), local attention runs, a second
    all-to-all restores sequence sharding.
    """
    p = axis_size(axis_name)
    if q.shape[2] % p != 0:
        raise ValueError(f"ulysses needs heads ({q.shape[2]}) divisible by axis size ({p})")

    def seq_to_heads(x):  # [B, T/P, H, D] -> [B, T, H/P, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):  # [B, T, H/P, D] -> [B, T/P, H, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    out = attention(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v), causal=causal, scale=scale)
    return heads_to_seq(out)


def ring_self_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    seq_axis: str = SEQ_AXIS,
    batch_axis: str = DATA_AXIS,
    causal: bool = False,
    impl: str = "ring",
) -> jnp.ndarray:
    """Global-view wrapper: ``[B, T, H, D]`` in, same out, T sharded over
    ``seq_axis`` (and B over ``batch_axis`` when the mesh has one)."""
    if q.shape[1] % mesh.shape[seq_axis] != 0:
        raise ValueError(
            f"sequence length {q.shape[1]} must divide over seq axis {mesh.shape[seq_axis]}. "
            "With causal=True you can right-pad q/k/v (parallel.mesh.pad_to_multiple) and "
            "slice the output — padded positions sit in the future and cannot affect real "
            "ones. With causal=False there is no key-padding mask, so padding would let "
            "every query attend to the pad keys; pad the batch layout upstream instead."
        )
    fn = {"ring": ring_attention, "ulysses": ulysses_attention}[impl]
    ba = batch_axis if batch_axis in mesh.shape else None
    spec = P(ba, seq_axis)
    local = functools.partial(fn, axis_name=seq_axis, causal=causal)
    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)
