"""Parameter/optimizer sharding layer: spec assignment over the ``'model'`` axis.

This is the GSPMD-style partitioning pattern (one program for a giant virtual
device, ``PartitionSpec`` on inputs/outputs, XLA inserts the collectives)
applied to the *parameter and optimizer trees* of a train step. The spec
assignment is a pure pytree pass over leaf **shapes**:

- a leaf whose byte size clears ``min_shard_bytes`` gets its **largest dim
  divisible by the model-axis size** sharded over ``'model'``;
- per-module regex overrides can pin the sharded dim (or force replication)
  for leaves the heuristic would split badly;
- everything else (scalars, small biases, layer norms) stays replicated —
  sharding them would cost more in collective latency than it saves in HBM.

Because the pass only looks at shapes, optimizer-state leaves (optax ``mu`` /
``nu`` mirror the param shapes) inherit the param layout with no extra
bookkeeping, and the same plan built from a restored host checkpoint re-specs
it onto a *different* ``model_axis`` on resume.

The algos never construct ``NamedSharding``/``PartitionSpec`` themselves
(``tools/lint_sharding.py`` enforces this): they ask
:meth:`sheeprl_tpu.fabric.Fabric.shard_plan` for a :class:`ShardingPlan` and
hand its shardings to ``jit(..., in_shardings/out_shardings)``.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Dict, List, Mapping, Optional, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sheeprl_tpu.parallel.mesh import MODEL_AXIS

__all__ = [
    "DEFAULT_MIN_SHARD_BYTES",
    "ShardingPlan",
    "assign_spec",
    "leaf_path_str",
    "make_plan",
    "measured_bytes_per_device",
]

#: Leaves smaller than this stay replicated: at 16 KiB the all-gather latency
#: of re-materializing a sharded leaf already dwarfs the per-device HBM saved.
DEFAULT_MIN_SHARD_BYTES = 1 << 14

#: Override value meaning "keep this leaf replicated regardless of size".
REPLICATE = "replicate"

_is_spec = lambda x: isinstance(x, P)  # noqa: E731 — shared is_leaf predicate


def leaf_path_str(path: Tuple[Any, ...]) -> str:
    """``tree_flatten_with_path`` keypath → ``"params/dense_0/kernel"``."""
    parts: List[str] = []
    for key in path:
        if isinstance(key, jax.tree_util.DictKey):
            parts.append(str(key.key))
        elif isinstance(key, jax.tree_util.SequenceKey):
            parts.append(str(key.idx))
        elif isinstance(key, jax.tree_util.GetAttrKey):
            parts.append(str(key.name))
        elif isinstance(key, jax.tree_util.FlattenedIndexKey):
            parts.append(str(key.key))
        else:  # unknown key type: strip the pretty-print punctuation
            parts.append(str(key).strip(".[]'\""))
    return "/".join(parts)


def _leaf_nbytes(leaf: Any) -> int:
    shape = tuple(getattr(leaf, "shape", ()) or ())
    itemsize = np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
    return int(np.prod(shape, dtype=np.int64)) * itemsize if shape else itemsize


def assign_spec(
    shape: Tuple[int, ...],
    nbytes: int,
    *,
    axis_size: int,
    axis_name: str = MODEL_AXIS,
    min_shard_bytes: int = DEFAULT_MIN_SHARD_BYTES,
    override_dim: Optional[int] = None,
) -> P:
    """The largest-dim-divisible-by-N heuristic for one leaf.

    ``override_dim`` pins the sharded dimension (raising if it does not
    divide, so a bad override fails loudly instead of silently replicating);
    otherwise the largest dim divisible by ``axis_size`` is sharded, with
    ties broken toward the leading dim for determinism. Leaves below
    ``min_shard_bytes``, scalars, and leaves with no divisible dim fall back
    to replicated ``P()``.
    """
    shape = tuple(shape or ())
    if override_dim is not None:
        dim = override_dim if override_dim >= 0 else len(shape) + override_dim
        if dim < 0 or dim >= len(shape) or shape[dim] % axis_size != 0:
            raise ValueError(
                f"sharding override dim {override_dim} invalid for shape {shape} "
                f"with {axis_name}={axis_size}"
            )
        spec: List[Any] = [None] * len(shape)
        spec[dim] = axis_name
        return P(*spec)
    if axis_size <= 1 or not shape or nbytes < min_shard_bytes:
        return P()
    divisible = [(size, idx) for idx, size in enumerate(shape) if size and size % axis_size == 0]
    if not divisible:
        return P()
    _, best = max(divisible, key=lambda pair: (pair[0], -pair[1]))
    spec = [None] * len(shape)
    spec[best] = axis_name
    return P(*spec)


@dataclass(frozen=True)
class ShardingPlan:
    """A spec tree bound to a mesh: the one object algos shard through.

    ``specs`` mirrors the target pytree with a :class:`PartitionSpec` at
    every leaf position.
    """

    mesh: Mesh
    axis_name: str
    axis_size: int
    specs: Any

    def shardings(self) -> Any:
        """The spec tree as ``NamedSharding`` leaves (feeds ``in_shardings``/
        ``out_shardings`` and ``device_put``)."""
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), self.specs, is_leaf=_is_spec
        )

    def place(self, tree: Any) -> Any:
        """Commit a (host or replicated) tree onto the planned layout."""
        return jax.device_put(tree, self.shardings())

    def bytes_total(self, tree: Any) -> int:
        return int(
            sum(
                jax.tree_util.tree_leaves(
                    jax.tree_util.tree_map(_leaf_nbytes, tree)
                )
            )
        )

    def bytes_per_device(self, tree: Any) -> int:
        """Analytic per-device bytes under this plan: sharded leaves divide
        by ``axis_size``, replicated leaves are paid in full on every
        device."""

        def _per_device(leaf: Any, spec: P) -> int:
            nbytes = _leaf_nbytes(leaf)
            if any(entry == self.axis_name for entry in tuple(spec)):
                return -(-nbytes // self.axis_size)  # ceil for uneven pads
            return nbytes

        return int(
            sum(jax.tree_util.tree_leaves(jax.tree_util.tree_map(_per_device, tree, self.specs)))
        )

    def sharded_leaf_count(self) -> Tuple[int, int]:
        """``(sharded, total)`` leaf counts — plan summary for logs/manifest."""
        flat = jax.tree_util.tree_leaves(self.specs, is_leaf=_is_spec)
        sharded = sum(1 for s in flat if any(e == self.axis_name for e in tuple(s)))
        return sharded, len(flat)

    def describe(self) -> Dict[str, Any]:
        """JSON-able summary recorded in the checkpoint manifest: the mesh
        layout plus every leaf's spec, so a restore can verify what layout
        the shards were written under (the restore itself re-specs from the
        gathered host tree, so it never *needs* the old plan)."""
        flat, _ = jax.tree_util.tree_flatten_with_path(self.specs, is_leaf=_is_spec)
        sharded, total = self.sharded_leaf_count()
        return {
            "axis_name": self.axis_name,
            "axis_size": int(self.axis_size),
            "mesh_axes": {name: int(size) for name, size in self.mesh.shape.items()},
            "sharded_leaves": sharded,
            "total_leaves": total,
            "specs": {
                leaf_path_str(path): [
                    list(entry) if isinstance(entry, tuple) else entry for entry in tuple(spec)
                ]
                for path, spec in flat
            },
        }


def measured_bytes_per_device(tree: Any) -> int:
    """Per-device bytes of a *placed* tree, read off the actual shard shapes
    (each device holds one shard per array: a replicated leaf contributes its
    full size, a sharded leaf its slice). This is the measured counterpart of
    :meth:`ShardingPlan.bytes_per_device` and feeds the
    ``params_bytes_per_device`` telemetry gauge."""

    def _one(leaf: Any) -> int:
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            data = shards[0].data
            return int(np.prod(tuple(data.shape) or (1,), dtype=np.int64)) * np.dtype(
                data.dtype
            ).itemsize
        return _leaf_nbytes(leaf)

    return int(sum(jax.tree_util.tree_leaves(jax.tree_util.tree_map(_one, tree))))


def make_plan(
    tree: Any,
    mesh: Mesh,
    *,
    axis_name: str = MODEL_AXIS,
    min_shard_bytes: int = DEFAULT_MIN_SHARD_BYTES,
    overrides: Optional[Mapping[str, Union[int, str]]] = None,
) -> ShardingPlan:
    """Assign a PartitionSpec to every leaf of ``tree`` (arrays or
    ``ShapeDtypeStruct``s — only shapes/dtypes are read).

    ``overrides`` maps leaf-path regexes (matched with ``re.search`` against
    the ``"a/b/c"`` path) to either a dim index to shard or ``"replicate"``;
    the first matching pattern wins, in mapping order.
    """
    axis_size = int(mesh.shape.get(axis_name, 1))
    compiled: List[Tuple[re.Pattern, Union[int, str]]] = [
        (re.compile(pattern), rule) for pattern, rule in (overrides or {}).items()
    ]

    def _spec(path: Tuple[Any, ...], leaf: Any) -> P:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        nbytes = _leaf_nbytes(leaf)
        path_str = leaf_path_str(path)
        for pattern, rule in compiled:
            if pattern.search(path_str):
                if isinstance(rule, str) and rule.lower() in (REPLICATE, "replicated"):
                    return P()
                return assign_spec(
                    shape,
                    nbytes,
                    axis_size=axis_size,
                    axis_name=axis_name,
                    min_shard_bytes=min_shard_bytes,
                    override_dim=int(rule),
                )
        return assign_spec(
            shape,
            nbytes,
            axis_size=axis_size,
            axis_name=axis_name,
            min_shard_bytes=min_shard_bytes,
        )

    specs = jax.tree_util.tree_map_with_path(_spec, tree)
    return ShardingPlan(mesh=mesh, axis_name=axis_name, axis_size=axis_size, specs=specs)
