"""Phase spans: structured tracing layered on the wall-clock timer registry.

:class:`span` is a drop-in superset of :class:`sheeprl_tpu.utils.timer.timer`:
it accumulates wall seconds into the same global registry (so the
``Time/sps_*`` rate gauges keep working unchanged), and — when a run tracer is
active — additionally

- emits one Chrome trace-event per scope into a per-run JSONL file
  (``<log_dir>/telemetry/trace.jsonl``), and
- mirrors the scope into :class:`jax.profiler.TraceAnnotation`, so the same
  phase names show up inside XLA/TensorBoard device profiles captured with
  ``metric.profiler``.

The tracer is installed by :func:`sheeprl_tpu.obs.telemetry.setup_telemetry`;
with no tracer installed a ``span`` is exactly a ``timer`` (no file handles,
no jax calls, no device syncs), so instrumented code paths cost nothing in
un-instrumented runs.

Trace-event schema (one JSON object per line; the "complete event" subset of
the Chrome trace-event format):

``{"name": str, "cat": phase, "ph": "X", "ts": µs, "dur": µs,
  "pid": jax process index, "tid": host thread id}``

plus ``{"ph": "M", ...}`` thread-name metadata and ``{"ph": "C", ...}``
counter samples from the device poller. Load in Perfetto / chrome://tracing
after wrapping the lines in a JSON array (``jq -s . trace.jsonl``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import ContextDecorator
from typing import Any, Dict, Optional

from sheeprl_tpu.obs import hist as _hist
from sheeprl_tpu.utils.timer import timer

__all__ = ["span", "TraceWriter", "get_tracer", "set_tracer"]

#: events buffered before a file flush (bounds write syscalls in hot loops)
_FLUSH_EVERY = 128

_TRACER: Optional["TraceWriter"] = None


def get_tracer() -> Optional["TraceWriter"]:
    """The run's active tracer, or None (telemetry disabled)."""
    return _TRACER


def set_tracer(tracer: Optional["TraceWriter"]) -> None:
    global _TRACER
    _TRACER = tracer


class TraceWriter:
    """Thread-safe buffered Chrome trace-event JSONL writer.

    ``path=None`` runs the writer file-less: events are still produced (and
    fed to ``ring`` — the flight recorder's bounded buffer) but nothing
    touches the disk. That is how a run with ``metric.telemetry.trace=false``
    keeps its flight recorder armed.
    """

    def __init__(
        self,
        path: Optional[str] = None,
        xla_annotations: bool = True,
        ring=None,
        pid: Optional[int] = None,
        process_name: Optional[str] = None,
        origin: Optional[float] = None,
    ):
        self.path = path
        self.xla_annotations = bool(xla_annotations)
        self.ring = ring
        if path is not None:
            os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
            self._file = open(path, "w")
        else:
            self._file = None
        self._lock = threading.Lock()
        self._buffer: list[str] = []
        # writers sharing one process can share one origin so their ts
        # values compare directly (the serve tracer's two lanes do)
        self._origin = float(origin) if origin is not None else time.perf_counter()
        self._named_threads: set[int] = set()
        if pid is not None:
            # explicit track id: plane players and env workers must not
            # collide with the learner's pid 0 in a merged Perfetto view
            # (and must not import jax just to pick a number)
            self._pid = int(pid)
        else:
            try:
                import jax

                self._pid = int(jax.process_index())
            except Exception:
                self._pid = 0
        # wall-clock anchor so tools/trace_view.py can align per-rank files
        # captured by processes with different perf_counter origins
        self._emit(
            {
                "ph": "M",
                "name": "clock_sync",
                "pid": self._pid,
                "args": {"unix_ts": time.time()},
            }
        )
        if process_name:
            # Perfetto/chrome://tracing label the whole track with this
            self._emit(
                {
                    "ph": "M",
                    "name": "process_name",
                    "pid": self._pid,
                    "args": {"name": process_name},
                }
            )

    # -- time ---------------------------------------------------------------

    def now(self) -> float:
        """Monotonic seconds; pass to :meth:`complete` as the span start."""
        return time.perf_counter()

    def _us(self, t: float) -> float:
        return (t - self._origin) * 1e6

    # -- events -------------------------------------------------------------

    def _emit(self, event: Dict[str, Any]) -> None:
        if self.ring is not None:
            self.ring.record(event)
        if self._file is None:
            return
        line = json.dumps(event)
        with self._lock:
            self._buffer.append(line)
            if len(self._buffer) >= _FLUSH_EVERY:
                self._flush_locked()

    def _thread_meta(self, tid: int) -> None:
        if tid in self._named_threads:
            return
        self._named_threads.add(tid)
        self._emit(
            {
                "ph": "M",
                "name": "thread_name",
                "pid": self._pid,
                "tid": tid,
                "args": {"name": threading.current_thread().name},
            }
        )

    def complete(
        self,
        name: str,
        cat: Optional[str],
        t0: float,
        t1: Optional[float] = None,
        args: Optional[Dict[str, Any]] = None,
    ) -> None:
        """One completed span ``[t0, t1]`` (``ph: X``). ``args`` attaches
        correlation payload (e.g. a serve trace id) to the event."""
        t1 = time.perf_counter() if t1 is None else t1
        tid = threading.get_ident()
        self._thread_meta(tid)
        self._emit(
            {
                "name": name,
                "cat": cat or "run",
                "ph": "X",
                "ts": round(self._us(t0), 1),
                "dur": round((t1 - t0) * 1e6, 1),
                "pid": self._pid,
                "tid": tid,
                **({"args": args} if args else {}),
            }
        )

    def counter(self, name: str, values: Dict[str, float]) -> None:
        """A sampled counter series (``ph: C``) — e.g. per-device HBM use."""
        self._emit(
            {
                "name": name,
                "ph": "C",
                "ts": round(self._us(time.perf_counter()), 1),
                "pid": self._pid,
                "args": values,
            }
        )

    def instant(self, name: str, cat: Optional[str] = None, args: Optional[Dict[str, Any]] = None) -> None:
        """A zero-duration marker (``ph: i``) — e.g. a health-guard firing."""
        self._emit(
            {
                "name": name,
                "cat": cat or "health",
                "ph": "i",
                "s": "g",
                "ts": round(self._us(time.perf_counter()), 1),
                "pid": self._pid,
                "tid": threading.get_ident(),
                **({"args": args} if args else {}),
            }
        )

    def annotation(self, name: str):
        """A ``jax.profiler.TraceAnnotation`` for the span, or None."""
        if not self.xla_annotations:
            return None
        try:
            import jax

            return jax.profiler.TraceAnnotation(name)
        except Exception:
            return None

    # -- lifecycle ----------------------------------------------------------

    def _flush_locked(self) -> None:
        if self._buffer and self._file is not None and not self._file.closed:
            self._file.write("\n".join(self._buffer) + "\n")
            self._file.flush()
        self._buffer.clear()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        with self._lock:
            self._flush_locked()
            if self._file is not None and not self._file.closed:
                self._file.close()


class span(ContextDecorator):
    """``with span("Time/train_time", phase="train"): ...``

    Accumulates into the global :class:`timer` registry under ``name`` (same
    semantics, including the concurrent-reset re-register path) and, when a
    tracer is active, emits a trace event categorized under ``phase`` and
    mirrors the scope into the XLA profiler.
    """

    def __init__(self, name: str, metric: Any = None, phase: Optional[str] = None):
        self.name = name
        self.phase = phase
        self._timer = timer(name, metric)
        self._t0: Optional[float] = None
        self._annotation = None

    def __enter__(self):
        tracer = _TRACER
        if tracer is not None or _hist.installed() is not None:
            self._t0 = time.perf_counter()
        if tracer is not None:
            self._annotation = tracer.annotation(self.name)
            if self._annotation is not None:
                self._annotation.__enter__()
        self._timer.__enter__()
        return self

    def __exit__(self, *exc):
        self._timer.__exit__(*exc)
        if self._annotation is not None:
            self._annotation.__exit__(*exc)
            self._annotation = None
        if self._t0 is not None:
            t0, self._t0 = self._t0, None
            t1 = time.perf_counter()
            # histograms first: a slow-span trigger fired here lands its
            # flight dump before this very event rotates into the ring
            _hist.observe(self.name, t1 - t0)
            tracer = _TRACER
            if tracer is not None:
                tracer.complete(self.name, self.phase, t0, t1)
        return False
