"""Per-request distributed tracing for the serving tier (Dapper-style).

A sampled ``act()`` request carries one trace id from the client call site
through the slab ring / batcher queue to the device dispatch, and lands as a
six-stage span chain in the run's Chrome-trace plane::

    client_enqueue -> ring_transit -> queue_wait -> batch_assembly
                   -> device_dispatch -> respond

The first two stages live on a **client lane** (their own Perfetto pid) and
the last four on a **gateway lane**, both written as ``trace_serve_*.jsonl``
so ``tools/trace_view.py`` merges them with the learner's trace onto one
clock. All stamps are ``time.perf_counter()`` — CLOCK_MONOTONIC on Linux is
system-wide, so stamps a ring client wrote in another process compare
directly against the gateway's.

Sampling is deterministic (every k-th request for ``serve.trace_sample_rate
= 1/k``) so a seeded run always traces the same requests. With no tracer
installed — or ``trace_sample_rate: 0`` — :func:`sample` is one global read
returning None, and the request path does no extra work (the PR-4 span
contract: instrumented code costs nothing in un-instrumented runs).

This module is also the **sanctioned clock chokepoint** for ``serve/``:
``tools/lint_telemetry.py`` rejects ad-hoc ``time.time()`` /
``time.monotonic()`` / ``time.perf_counter()`` reads in the serving tier so
every request timestamp flows through :func:`now` / :func:`unix_now` and
stays comparable across the trace, latency-histogram, and SLO planes.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

__all__ = [
    "CLIENT_PID",
    "GATEWAY_PID",
    "STAGES",
    "RequestTrace",
    "ServeTracer",
    "install",
    "installed",
    "now",
    "sample",
    "unix_now",
]

#: the six per-request stages, in causal order
STAGES = (
    "client_enqueue",
    "ring_transit",
    "queue_wait",
    "batch_assembly",
    "device_dispatch",
    "respond",
)

#: fixed Perfetto track ids for the two serve lanes (learner is pid 0, plane
#: players/env workers use small offsets — keep the serve lanes far away)
GATEWAY_PID = 9000
CLIENT_PID = 9100


def now() -> float:
    """Monotonic seconds — the one clock every serve/ timestamp comes from."""
    return time.perf_counter()


def unix_now() -> float:
    """Wall-clock seconds for human-facing records (access log, alerts)."""
    return time.time()


class RequestTrace:
    """The per-request baton: a trace id plus the two client-side stamps.

    Rides ``_Pending`` through the batcher (local clients) or the slab
    ring's slot-metadata block (process clients); span emission happens
    once, gateway-side, when the dispatch that served the request retires.
    """

    __slots__ = ("trace_id", "t_start", "t_enqueue")

    def __init__(self, trace_id: int, t_start: float, t_enqueue: float = 0.0):
        self.trace_id = int(trace_id)
        self.t_start = float(t_start)
        self.t_enqueue = float(t_enqueue)


class ServeTracer:
    """Two-lane trace writer + deterministic sampler for the serving tier."""

    def __init__(self, out_dir: str, sample_rate: float, flight_ring=None):
        from sheeprl_tpu.obs.spans import TraceWriter

        rate = float(sample_rate)
        self.sample_rate = max(0.0, min(rate, 1.0))
        #: sample every k-th request (k=1 when rate>=1; rate<=0 disables)
        self._every = 1 if self.sample_rate >= 1.0 else (
            max(1, round(1.0 / self.sample_rate)) if self.sample_rate > 0 else 0
        )
        self._lock = threading.Lock()
        self._seen = 0
        self.sampled = 0
        os.makedirs(out_dir, exist_ok=True)
        self.client = TraceWriter(
            path=os.path.join(out_dir, "trace_serve_client.jsonl"),
            xla_annotations=False,
            ring=flight_ring,
            pid=CLIENT_PID,
            process_name="serve_client",
        )
        self.gateway = TraceWriter(
            path=os.path.join(out_dir, "trace_serve_gateway.jsonl"),
            xla_annotations=False,
            ring=flight_ring,
            pid=GATEWAY_PID,
            process_name="serve_gateway",
            # one shared origin: spans on the two lanes order correctly
            # without relying on the clock_sync anchors' ms precision
            origin=self.client._origin,
        )

    def sample(self) -> Optional[RequestTrace]:
        """A fresh :class:`RequestTrace` for every k-th request, else None."""
        if self._every <= 0:
            return None
        with self._lock:
            self._seen += 1
            if (self._seen - 1) % self._every:
                return None
            trace_id = self._seen
        return RequestTrace(trace_id, t_start=now())

    def emit_request(
        self,
        trace: RequestTrace,
        t_submit: float,
        t_collect: float,
        t_model: float,
        t_done: float,
        t_end: float,
        client_id: str = "",
        version: int = 0,
    ) -> None:
        """Write the full six-stage chain for one retired request."""
        args: Dict[str, Any] = {"trace_id": trace.trace_id}
        if client_id:
            args["client"] = str(client_id)
        if version:
            args["version"] = int(version)
        t_enqueue = trace.t_enqueue or trace.t_start
        self.client.complete("serve/client_enqueue", "serve", trace.t_start, t_enqueue, args=args)
        self.client.complete("serve/ring_transit", "serve", t_enqueue, t_submit, args=args)
        self.gateway.complete("serve/queue_wait", "serve", t_submit, t_collect, args=args)
        self.gateway.complete("serve/batch_assembly", "serve", t_collect, t_model, args=args)
        self.gateway.complete("serve/device_dispatch", "serve", t_model, t_done, args=args)
        self.gateway.complete("serve/respond", "serve", t_done, t_end, args=args)
        with self._lock:
            self.sampled += 1
        from sheeprl_tpu.obs.counters import add_serve_traced

        add_serve_traced(1)

    def close(self) -> None:
        self.client.close()
        self.gateway.close()


_TRACER: Optional[ServeTracer] = None


def install(tracer: Optional[ServeTracer]) -> None:
    """Activate (or with ``None`` deactivate) the serve request tracer."""
    global _TRACER
    _TRACER = tracer


def installed() -> Optional[ServeTracer]:
    return _TRACER


def sample() -> Optional[RequestTrace]:
    """Client-side entry: a trace baton for this request, or None (the
    common case — one global read when tracing is off)."""
    tracer = _TRACER
    return None if tracer is None else tracer.sample()
