"""The run telemetry object: configuration, lifecycle, and the end-of-run
summary (``telemetry.json``).

One :class:`Telemetry` exists per training run, configured from the
``metric.telemetry`` config group and owned by the CLI
(:func:`sheeprl_tpu.cli.run_algorithm` calls :func:`setup_telemetry` before
launching and :func:`finalize_telemetry` after). Algorithms and the data
layer never see the object directly — they use :func:`get_telemetry` (None
when disabled), the :class:`~sheeprl_tpu.obs.spans.span` scopes, and the
counter helpers, all of which are no-ops in un-instrumented runs.

On finalize the run's aggregate health is printed and written as
``telemetry.json`` next to the checkpoint dir (``<log_dir>/telemetry.json``):

========================  ====================================================
key                       meaning
========================  ====================================================
``run_wall_s``            wall seconds between setup and finalize
``policy_steps``          per-process env steps accounted at log boundaries
``train_steps``           gradient steps accounted at log boundaries
``sps``                   policy_steps / run_wall_s (whole-run average)
``sps_env``               policy_steps / timed env-interaction seconds
``sps_train``             train_steps / timed train seconds
``mfu``                   % of ``peak_tflops`` sustained during timed train
                          seconds (null until an algo registers step FLOPs)
``bytes_staged_h2d``      bytes shipped host→device through the staging paths
``h2d_transfers``         number of staged transfers
``recompiles``            XLA backend compiles observed (jax.monitoring)
``compile_secs``          seconds spent in backend compilation
``compile_cache_hits``    persistent-compilation-cache hits
``peak_hbm_bytes``        peak device ``bytes_in_use`` seen by the poller
``hbm_bytes_limit``       device memory limit (0 where the runtime hides it)
``nonfinite_metrics``     NaN/inf values caught by the loss guard
``learn_warnings``        warn-grade learning-health events (obs/learn)
``learn_criticals``       critical-grade learning-health events (sustained
                          grad explosion, non-finite grads/metrics)
``grad_norm_p95``         p95 global gradient norm over the run (null until
                          the learn sentinel observed a burst)
``update_ratio_p50``      median update-to-weight ratio (same plane)
``learn``                 the sentinel's sub-dict: event list, per-probe
                          baselines, ``first_nonfinite_ts``
``stalls``                watchdog stall episodes
``ckpt_blocked_ms``       train-step wall ms blocked on checkpoints (host
                          snapshot + double-buffer wait — the step-path cost)
``ckpt_write_ms``         writer-thread ms spent serializing/fsyncing saves
``ckpt_bytes``            checkpoint bytes landed on disk
``ckpt_saves``            completed checkpoint writes
``ckpt_failures``         writes that exhausted their retry budget
``env_steps_async``       env steps served by the async shared-memory pool
``env_worker_restarts``   env workers restarted after a crash/hang
``env_degraded_to_sync``  1 when the pool exhausted its restart budget and
                          fell back to in-process sync stepping
``phase_percentiles``     per-phase ``p50/p95/p99`` span durations (ms) from
                          the streaming histograms (``obs/hist.py``)
``device_ms_per_step``    profiled device time per train-step unit from the
                          latest in-run capture (``obs/prof``; null until a
                          ``metric.telemetry.profile`` window landed)
``mfu_device_pct``        MFU against measured *device* time (vs ``mfu``'s
                          timed-wall basis) from the same capture
``roofline_verdict``      ``compute-bound`` / ``memory-bound`` /
                          ``dispatch-bound`` binding-constraint verdict
``prof_captures``         in-run profile captures parsed this run
``flight_dumps``          flight-recorder evidence files written
``crashed``               True when the entrypoint raised; ``exception``
                          then carries the type and message
========================  ====================================================

The same object owns the **live plane** (``obs/live.py``): a periodic
exporter that atomically rewrites ``telemetry/live.json`` with this summary
plus rolling-window rates and watchdog beat ages, an optional Prometheus
endpoint, and the anomaly-triggered flight recorder.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

from sheeprl_tpu.obs import counters as _counters
from sheeprl_tpu.obs import hist as _hist
from sheeprl_tpu.obs.health import NonFiniteGuard, StallWatchdog
from sheeprl_tpu.obs.live import FlightRecorder, LiveExporter, PromServer, atomic_write_json
from sheeprl_tpu.obs.perf import PEAK_TFLOPS_BF16, mfu_pct
from sheeprl_tpu.obs.spans import TraceWriter, set_tracer

__all__ = ["Telemetry", "setup_telemetry", "get_telemetry", "finalize_telemetry"]

_ACTIVE: Optional["Telemetry"] = None


def get_telemetry() -> Optional["Telemetry"]:
    """The active run telemetry, or None when disabled."""
    return _ACTIVE


def _process_index() -> int:
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


class Telemetry:
    def __init__(self, tcfg: Optional[Dict[str, Any]] = None):
        tcfg = dict(tcfg or {})
        self.cfg = tcfg
        self.trace_enabled = bool(tcfg.get("trace", True))
        self.trace_file: Optional[str] = tcfg.get("trace_file") or None
        self.xla_annotations = bool(tcfg.get("xla_annotations", True))
        self.poll_interval_s = float(tcfg.get("poll_interval_s", 5.0) or 0.0)
        self.stall_timeout_s = float(tcfg.get("stall_timeout_s", 120.0) or 0.0)
        self.summary_enabled = bool(tcfg.get("summary", True))
        self.summary_path: Optional[str] = tcfg.get("summary_path") or None
        self.peak_tflops = float(tcfg.get("peak_tflops", PEAK_TFLOPS_BF16))
        # live plane (obs/live.py)
        self.live_interval_s = float(tcfg.get("live_interval_s", 30.0) or 0.0)
        self.live_window_s = float(tcfg.get("live_window_s", 60.0) or 60.0)
        self.serve_port: Optional[int] = (
            int(tcfg.get("serve_port") or 0) or None
        )
        self.histograms_enabled = bool(tcfg.get("histograms", True))
        self.staleness_enabled = bool(tcfg.get("staleness", True))
        self._flight_cfg = dict(tcfg.get("flight", {}) or {})
        self._profile_cfg = dict(tcfg.get("profile", {}) or {})

        self.counters = _counters.Counters()
        self.staleness = None  # StalenessTracker, built in start()
        self.sentinel = None  # LearnSentinel (obs/learn), built in start()
        self.tracer: Optional[TraceWriter] = None
        self.poller: Optional[_counters.DevicePoller] = None
        self.guard: Optional[NonFiniteGuard] = None
        self.hists: Optional[_hist.HistogramSet] = None
        self.flight: Optional[FlightRecorder] = None
        self.live: Optional[LiveExporter] = None
        self.prom: Optional[PromServer] = None
        self.run_dir: Optional[str] = None
        self._rank = 0
        self._watchdogs: list[StallWatchdog] = []
        self._t_start = time.perf_counter()
        self._finalized = False
        self._printed_trace_note = False

        # accumulated at log boundaries by perf.log_sps_metrics
        self.policy_steps = 0
        self.train_steps = 0
        self.env_seconds = 0.0
        self.train_seconds = 0.0
        self.stage_seconds = 0.0
        #: FLOPs per *unit of the train-step counter* (which advances by
        #: world_size per dispatched program): register program_flops /
        #: world_size so `flops_per_train_step × Δtrain_step` is the
        #: per-device FLOPs actually executed — the MFU numerator against the
        #: single-chip `peak_tflops`
        self.flops_per_train_step: Optional[float] = None
        #: bytes accessed per train-step unit (same convention) — the
        #: bandwidth numerator of the in-run roofline (obs/prof)
        self.bytes_per_train_step: Optional[float] = None
        #: program dispatches per train-step unit (families that loop a
        #: single-gradient-step program register per_rank_gradient_steps)
        self.dispatches_per_train_step = 1
        self._flops_attempted = False
        # in-run device-profile capture (obs/prof/capture.py); built in
        # start() so profile_tick is a no-op on un-instrumented runs
        self.prof = None
        self._prof_last: Optional[Dict[str, Any]] = None
        #: last world_size seen at a profile_tick — anomaly-capture parses
        #: (obs/prof.parse_and_fold) scale per-unit numbers with it
        self.last_world_size = 1

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        from sheeprl_tpu.obs.dist import aggregate as _aggregate
        from sheeprl_tpu.obs.dist import staleness as _staleness
        from sheeprl_tpu.obs.prof.capture import StepProfiler

        self.prof = StepProfiler(self._profile_cfg, self)
        _counters.install(self.counters)
        _aggregate.clear_sources()
        if self.staleness_enabled:
            self.staleness = _staleness.StalenessTracker()
            _staleness.install(self.staleness)
        if self.poll_interval_s > 0:
            self.poller = _counters.DevicePoller(self.poll_interval_s)
            self.poller.start()
        fcfg = self._flight_cfg
        if bool(fcfg.get("enabled", True)):
            self.flight = FlightRecorder(
                capacity=int(fcfg.get("ring_events", 2048)),
                min_interval_s=float(fcfg.get("min_interval_s", 30.0)),
                max_dumps=int(fcfg.get("max_dumps", 8)),
                profiler_capture_s=float(fcfg.get("profiler_capture_s", 0.0) or 0.0),
                step_source=lambda: self.policy_steps,
                context_fn=self._flight_context,
            )
            self._recompile_warmup_s = float(fcfg.get("recompile_warmup_s", 120.0))
            _counters.set_compile_hook(self._on_compile)
        if self.histograms_enabled:
            self.hists = _hist.HistogramSet(
                slow_factor=(
                    float(fcfg.get("slow_span_factor", 8.0)) if self.flight is not None else 0.0
                ),
                slow_warmup=int(fcfg.get("slow_span_warmup", 64)),
                slow_min_s=float(fcfg.get("slow_span_min_ms", 100.0)) / 1e3,
                on_slow=self._on_slow_span if self.flight is not None else None,
            )
            _hist.install(self.hists)
        lcfg = dict(self.cfg.get("learn", {}) or {})
        if bool(lcfg.get("enabled", True)):
            from sheeprl_tpu.obs import learn as _learn

            self.sentinel = _learn.LearnSentinel(
                lcfg,
                counters=self.counters,
                flight=self.flight,
                step_source=lambda: self.policy_steps,
            )
            _learn.install(self.sentinel)
        guard_cfg = self.cfg.get("health", {}) or {}
        if bool(guard_cfg.get("nan_guard", True)):
            self.guard = NonFiniteGuard(
                prefixes=tuple(guard_cfg.get("nan_guard_prefixes", ("Loss/", "Grads/"))),
                raise_on_nonfinite=bool(guard_cfg.get("raise_on_nonfinite", False)),
                counters=self.counters,
                # terminal stage: flight evidence dump AND the learn
                # sentinel's first_nonfinite timestamp (acceptance ordering)
                on_fire=(
                    self._on_nonfinite
                    if (self.flight is not None or self.sentinel is not None)
                    else None
                ),
            )
            from sheeprl_tpu.utils.metric import set_value_guard

            set_value_guard(self.guard)
        if self.trace_file:  # explicit path: trace from the very beginning
            self._open_tracer(self.trace_file)
        elif not self.trace_enabled and self.flight is not None:
            # no trace file wanted, but the flight recorder still needs the
            # event stream: run the writer file-less from the start
            self._open_tracer(None)

    def _open_tracer(self, path: Optional[str], process_name: Optional[str] = None) -> None:
        if self.tracer is not None:
            return
        file_path = path if self.trace_enabled else None
        if file_path is None and self.flight is None:
            return
        self.tracer = TraceWriter(
            file_path,
            xla_annotations=self.xla_annotations,
            ring=self.flight,
            process_name=process_name,
        )
        set_tracer(self.tracer)

    def attach_run_dir(self, log_dir: str) -> None:
        """Called once the versioned run directory exists (logger layer).

        Rank 0 owns the summary, the live exporter, and ``trace.jsonl``;
        other ranks write per-rank trace files (``trace_rank<k>.jsonl``,
        merged by ``tools/trace_view.py``) and dump their histograms at
        finalize for rank 0's cross-rank percentile merge."""
        if not log_dir or self.run_dir is not None:
            return
        self.run_dir = log_dir
        self._rank = _process_index()
        tel_dir = os.path.join(log_dir, "telemetry")
        if self.flight is not None:
            self.flight.attach_dir(
                tel_dir, tag="" if self._rank == 0 else f"_r{self._rank}"
            )
        if self._rank != 0:
            self._open_tracer(
                os.path.join(tel_dir, f"trace_rank{self._rank}.jsonl"),
                process_name=f"rank{self._rank}",
            )
            return
        if self.summary_path is None:
            self.summary_path = os.path.join(log_dir, "telemetry.json")
        self._open_tracer(os.path.join(tel_dir, "trace.jsonl"), process_name="learner")
        if self.live_interval_s > 0 or self.serve_port:
            self.live = LiveExporter(
                self._live_snapshot,
                os.path.join(tel_dir, "live.json"),
                interval_s=self.live_interval_s,
                window_s=self.live_window_s,
            )
            self.live.start()
            if self.serve_port is not None:
                try:
                    self.prom = PromServer(self.live, self.serve_port)
                    self.prom.start()
                except OSError as exc:
                    import warnings

                    warnings.warn(
                        f"telemetry: cannot serve metrics on port "
                        f"{self.serve_port}: {exc}"
                    )

    def watchdog(self, **kwargs) -> StallWatchdog:
        """A stall watchdog wired to this run's counters and timeout config.

        The telemetry stops it at finalize; callers still stop it eagerly
        when their threads exit so a finished run is not flagged. A stall
        additionally fires the flight recorder, so the evidence ring is
        dumped while the wedged thread is still wedged."""
        kwargs.setdefault("timeout_s", self.stall_timeout_s)
        user_on_stall = kwargs.pop("on_stall", None)
        flight = self.flight
        if flight is not None:

            def _on_stall(role: str, age_s: float) -> None:
                flight.trigger("stall", {"role": role, "age_s": round(age_s, 1)})
                if user_on_stall is not None:
                    user_on_stall(role, age_s)

            kwargs["on_stall"] = _on_stall
        elif user_on_stall is not None:
            kwargs["on_stall"] = user_on_stall
        dog = StallWatchdog(counters=self.counters, **kwargs)
        self._watchdogs.append(dog)
        return dog

    # -- flight-recorder triggers -------------------------------------------

    def _flight_context(self) -> Dict[str, Any]:
        return {
            "counters": self.counters.as_dict(),
            "phase_percentiles": self.hists.percentiles() if self.hists is not None else {},
        }

    def _on_slow_span(self, name: str, seconds: float, p50: float) -> None:
        self.flight.trigger(
            "slow_span",
            {
                "span": name,
                "duration_ms": round(seconds * 1e3, 3),
                "running_p50_ms": round(p50 * 1e3, 3),
            },
        )

    def _on_compile(self, duration_s: float) -> None:
        # cold-start compiles are expected; only a POST-warmup recompile (a
        # shape/dtype leaking into a jitted signature mid-run) is an anomaly
        if time.perf_counter() - self._t_start < self._recompile_warmup_s:
            return
        self.flight.trigger("recompile", {"compile_s": round(duration_s, 3)})

    def _on_nonfinite(self, name: str, value: float) -> None:
        if self.flight is not None:
            self.flight.trigger("nonfinite", {"metric": name, "value": str(value)})
        if self.sentinel is not None:
            self.sentinel.on_nonfinite(name, value)

    def _live_snapshot(self) -> Dict[str, Any]:
        snap = self.summary()
        snap["watchdog_beat_age_s"] = {
            role: info
            for dog in self._watchdogs
            for role, info in dog.beat_ages().items()
        }
        # fold any source sidecars already on disk (exited players, closed
        # env pools, other ranks) into the live view too — live.json is the
        # same merged shape as the final telemetry.json. Staleness dumps are
        # NOT merged here (that exact merge runs once, at finalize — doing
        # it per live write would double-count).
        if self.run_dir:
            from sheeprl_tpu.obs.dist import aggregate as _aggregate

            _aggregate.merge_into_summary(
                snap, os.path.join(self.run_dir, "telemetry"), None
            )
        return snap

    # -- run accounting -----------------------------------------------------

    def record_window(
        self,
        policy_steps: int = 0,
        train_steps: int = 0,
        env_seconds: float = 0.0,
        train_seconds: float = 0.0,
        stage_seconds: float = 0.0,
    ) -> None:
        self.policy_steps += int(policy_steps)
        self.train_steps += int(train_steps)
        self.env_seconds += float(env_seconds)
        self.train_seconds += float(train_seconds)
        self.stage_seconds += float(stage_seconds)

    def set_train_flops(self, flops_per_step: Optional[float]) -> None:
        """Register per-train-step-unit FLOPs (None records the attempt, so a
        backend without cost analysis is probed once, not every update)."""
        self._flops_attempted = True
        if flops_per_step:
            self.flops_per_train_step = float(flops_per_step)

    def set_train_cost(
        self,
        flops_per_step: Optional[float],
        bytes_per_step: Optional[float] = None,
        dispatches_per_step: int = 1,
    ) -> None:
        """Register the train program's full analytic cost (FLOPs + bytes
        accessed, per train-step unit) — ``obs.register_train_cost`` calls
        this; the bytes side feeds the roofline's bandwidth axis and
        ``dispatches_per_step`` maps profiled per-execution device time back
        onto train-step units (obs/prof/capture.py)."""
        self.set_train_flops(flops_per_step)
        if bytes_per_step:
            self.bytes_per_train_step = float(bytes_per_step)
        self.dispatches_per_train_step = max(int(dispatches_per_step), 1)

    def record_prof(self, record: Dict[str, Any]) -> None:
        """Latest in-run profile result (StepProfiler / flight-recorder
        capture) — folded into summary(), live.json, and telemetry.json.
        A window that caught no train execution (``device_ms_per_step``
        null) never replaces an earlier measured one, and a slow parse of an
        OLD capture landing out of order never replaces a newer measured
        one: the run summary keeps the best, freshest evidence."""
        prev = self._prof_last
        if record.get("device_ms_per_step") is None and prev is not None:
            return
        if (
            prev is not None
            and prev.get("device_ms_per_step") is not None
            and isinstance(prev.get("step"), int)
            and isinstance(record.get("step"), int)
            and record["step"] < prev["step"]
        ):
            return
        self._prof_last = record

    def needs_train_flops(self) -> bool:
        """Should the algorithm spend one AOT cost-analysis on its program?"""
        return not self._flops_attempted and self.flops_per_train_step is None

    # -- summary ------------------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        wall = time.perf_counter() - self._t_start
        # no windows were ever accounted (metric.log_level=0 disables the log
        # boundaries that feed record_window): report null, not a fake 0.0 —
        # the counters below are still exact
        accounted = self.policy_steps > 0 or self.train_steps > 0
        out: Dict[str, Any] = {
            "run_wall_s": round(wall, 3),
            "policy_steps": self.policy_steps if accounted else None,
            "train_steps": self.train_steps if accounted else None,
            "sps": round(self.policy_steps / wall, 3) if wall > 0 and accounted else None,
            "sps_env": (
                round(self.policy_steps / self.env_seconds, 3) if self.env_seconds else None
            ),
            "sps_train": (
                round(self.train_steps / self.train_seconds, 3) if self.train_seconds else None
            ),
            "mfu": mfu_pct(
                self.flops_per_train_step,
                self.train_steps,
                self.train_seconds,
                self.peak_tflops,
            ),
            "mfu_peak_tflops": self.peak_tflops,
            "flops_per_train_step": self.flops_per_train_step,
            "bytes_per_train_step": self.bytes_per_train_step,
            "env_seconds": round(self.env_seconds, 3),
            "train_seconds": round(self.train_seconds, 3),
            "stage_seconds": round(self.stage_seconds, 3),
        }
        out.update(self.counters.as_dict())
        out.update(
            self.poller.snapshot()
            if self.poller is not None
            else {"peak_hbm_bytes": 0, "hbm_bytes_limit": 0, "hbm_samples": 0}
        )
        out["phase_percentiles"] = (
            self.hists.percentiles() if self.hists is not None else {}
        )
        out["flight_dumps"] = self.flight.dumps if self.flight is not None else 0
        out["flight_suppressed"] = self.flight.suppressed if self.flight is not None else 0
        # in-run device profile (obs/prof): the latest capture's headline
        # numbers as first-class summary keys, the detail as a sub-dict
        p = self._prof_last
        out["device_ms_per_step"] = p.get("device_ms_per_step") if p else None
        out["mfu_device_pct"] = p.get("mfu_device_pct") if p else None
        out["roofline_verdict"] = p.get("roofline_verdict") if p else None
        out["prof_captures"] = self.prof.captures if self.prof is not None else 0
        if p is not None:
            out["prof"] = {
                k: p.get(k)
                for k in (
                    "step",
                    "source",
                    "train_module",
                    "comms_ms_per_step",
                    "compute_ms_per_step",
                    "achieved_gbps",
                    "bandwidth_util_pct",
                    "arithmetic_intensity",
                    "busy_frac",
                    "window_ms",
                )
            }
            out["prof"]["peaks"] = (p.get("peaks") or {}).get("label")
        # learning health (obs/learn): headline percentiles flat (Prometheus
        # exports scalars), the event/baseline detail as a sub-dict
        if self.sentinel is not None:
            out["grad_norm_p95"] = self.sentinel.quantile("learn/grad_norm", 0.95)
            out["update_ratio_p50"] = self.sentinel.quantile("learn/update_ratio", 0.50)
            out["learn"] = self.sentinel.summary()
        else:
            out["grad_norm_p95"] = None
            out["update_ratio_p50"] = None
        # distributed observability (obs/dist): data-staleness lineage plus
        # the per-source breakdown of every process feeding this run
        staleness = self.staleness.summary() if self.staleness is not None else None
        out["staleness"] = staleness
        age = (staleness or {}).get("sample_age_s") or {}
        lag = (staleness or {}).get("policy_lag_versions") or {}
        out["sample_age_p95_s"] = age.get("p95_s")
        out["policy_lag_p95"] = lag.get("p95_v")
        from sheeprl_tpu.obs.dist import aggregate as _aggregate

        sources = _aggregate.source_snapshots()
        if sources:
            out["sources"] = sources
        if self.tracer is not None and self.tracer.path:
            out["trace_file"] = self.tracer.path
        return out

    def _sync_rank_hists(self) -> None:
        """Cross-rank percentile merge over the shared run dir: ranks > 0
        dump their histograms at finalize, rank 0 merges whatever dumps have
        landed (best-effort — a rank finalizing after rank 0 is missed, the
        dumps stay on disk for offline merging via ``obs.hist``)."""
        if self.hists is None or not self.run_dir:
            return
        tel_dir = os.path.join(self.run_dir, "telemetry")
        if self._rank != 0:
            try:
                atomic_write_json(
                    os.path.join(tel_dir, f"hist_rank{self._rank}.json"),
                    self.hists.to_dict(),
                )
            except OSError:
                pass
            return
        import glob

        for path in sorted(glob.glob(os.path.join(tel_dir, "hist_rank*.json"))):
            try:
                with open(path) as f:
                    self.hists.merge_dict(json.load(f))
            except Exception:
                pass  # a torn/foreign dump must not break finalize

    def _merge_sources(self, summary: Dict[str, Any]) -> None:
        """Cross-process telemetry merge (obs/dist/aggregate): ranks > 0
        dump a full summary sidecar; rank 0 folds every sidecar (ranks,
        plane players, env pools) plus the live source registry into this
        run's final summary — ONE merged ``telemetry.json`` with summed
        rank counters, merged staleness percentiles, and a per-source
        breakdown under ``sources``."""
        from sheeprl_tpu.obs.dist import aggregate as _aggregate

        tel_dir = os.path.join(self.run_dir, "telemetry") if self.run_dir else None
        if self._rank != 0:
            if tel_dir is not None:
                sidecar = dict(summary)
                if self.staleness is not None:
                    sidecar["staleness_dump"] = self.staleness.to_dict()
                _aggregate.write_sidecar(tel_dir, f"rank{self._rank}", sidecar)
            return
        _aggregate.merge_into_summary(summary, tel_dir, self.staleness)
        if self.staleness is not None:
            # rank staleness dumps merged above — refresh the percentiles
            staleness = self.staleness.summary()
            summary["staleness"] = staleness
            age = (staleness or {}).get("sample_age_s") or {}
            lag = (staleness or {}).get("policy_lag_versions") or {}
            summary["sample_age_p95_s"] = age.get("p95_s")
            summary["policy_lag_p95"] = lag.get("p95_v")

    def finalize(
        self, print_summary: bool = True, error: Optional[BaseException] = None
    ) -> Optional[Dict[str, Any]]:
        if self._finalized:
            return None
        self._finalized = True
        if self.prof is not None:
            self.prof.close()  # an in-flight capture still lands its numbers
        for dog in self._watchdogs:
            dog.stop()
        if self.prom is not None:
            self.prom.stop()
        if self.live is not None:
            self.live.stop()  # writes the final live.json
        if self.poller is not None:
            self.poller.stop()
        if self.guard is not None:
            from sheeprl_tpu.utils.metric import set_value_guard

            set_value_guard(None)
        _counters.set_compile_hook(None)
        self._sync_rank_hists()
        summary = self.summary()
        self._merge_sources(summary)
        summary["crashed"] = error is not None
        if error is not None:
            summary["exception"] = f"{type(error).__name__}: {error}"[:300]
        if self.tracer is not None:
            set_tracer(None)
            self.tracer.close()
        _counters.install(None)
        _hist.install(None)
        if self.sentinel is not None:
            from sheeprl_tpu.obs import learn as _learn

            if _learn.installed() is self.sentinel:
                _learn.install(None)
        from sheeprl_tpu.obs.dist import staleness as _staleness

        if _staleness.installed() is self.staleness:
            _staleness.install(None)
        if self.summary_enabled and self.summary_path and self._rank == 0:
            os.makedirs(os.path.dirname(os.path.abspath(self.summary_path)), exist_ok=True)
            with open(self.summary_path, "w") as f:
                json.dump(summary, f, indent=2, sort_keys=True)
                f.write("\n")
        if print_summary:
            self._print(summary)
        return summary

    def _print(self, s: Dict[str, Any]) -> None:
        try:
            import jax

            if jax.process_index() != 0:
                return
        except Exception:
            pass

        def fmt_bytes(n):
            if not n:
                return "0 B"
            for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
                if abs(n) < 1024 or unit == "TiB":
                    return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
                n /= 1024

        steps = (
            f"policy steps {s['policy_steps']} (sps {s['sps']}) · "
            f"train steps {s['train_steps']}"
            + (f" (sps_train {s['sps_train']})" if s["sps_train"] else "")
            if s["policy_steps"] is not None
            else "steps not accounted (metric.log_level=0)"
        )
        lines = [
            "── run telemetry "
            + "─" * 46,
            f"  wall {s['run_wall_s']:.1f}s · " + steps,
            f"  staged h2d {fmt_bytes(s['bytes_staged_h2d'])} over "
            f"{s['h2d_transfers']} transfers · recompiles {s['recompiles']} "
            f"({s['compile_secs']}s, {s['compile_cache_hits']} cache hits)",
            f"  peak HBM {fmt_bytes(s['peak_hbm_bytes'])}"
            + (f" / {fmt_bytes(s['hbm_bytes_limit'])}" if s["hbm_bytes_limit"] else "")
            + (f" · MFU {s['mfu']}%" if s["mfu"] is not None else "")
            + f" · non-finite {s['nonfinite_metrics']} · stalls {s['stalls']}",
        ]
        if s.get("device_ms_per_step") is not None:
            lines.append(
                f"  device {s['device_ms_per_step']} ms/step"
                + (
                    f" · MFU(dev) {s['mfu_device_pct']}%"
                    if s.get("mfu_device_pct") is not None
                    else ""
                )
                + f" · {s.get('roofline_verdict')}"
            )
        if s.get("env_steps_async") or s.get("env_worker_restarts"):
            lines.append(
                f"  async envs: {s['env_steps_async']} steps · "
                f"{s['env_worker_restarts']} worker restart(s)"
                + (" · DEGRADED TO SYNC" if s.get("env_degraded_to_sync") else "")
            )
        if s.get("comms_ops"):
            best = max(
                (k.get("best_gbps") or 0.0 for k in (s.get("comms") or {}).values()),
                default=0.0,
            )
            lines.append(
                f"  comms: {s['comms_ops']} collective(s) · "
                f"{fmt_bytes(s['comms_bytes'])} payload · {s['comms_ms']:.1f} ms"
                + (f" · best {best:.2f} GB/s wire" if best else "")
            )
        stale = s.get("staleness") or {}
        if stale.get("sample_age_s") or stale.get("policy_lag_versions"):
            age = stale.get("sample_age_s") or {}
            lag = stale.get("policy_lag_versions") or {}
            bits = []
            if age.get("p95_s") is not None:
                bits.append(f"sample age p50/p95 {age['p50_s']:.2f}/{age['p95_s']:.2f} s")
            if lag.get("p95_v") is not None:
                bits.append(f"policy lag p95 {lag['p95_v']:.1f} version(s)")
            lines.append("  staleness: " + " · ".join(bits))
        if s.get("sources"):
            lines.append(
                f"  sources merged: {', '.join(sorted(s['sources']))}"
            )
        if s.get("plane_traj_slabs") or s.get("plane_player_restarts"):
            lines.append(
                f"  plane: {s['plane_traj_slabs']} trajectory slab(s) · "
                f"policy v{s['plane_policy_version']} · "
                f"{s['plane_player_restarts']} player restart(s)"
            )
        if s["ckpt_saves"] or s["ckpt_failures"]:
            lines.append(
                f"  ckpt {s['ckpt_saves']} saves ({fmt_bytes(s['ckpt_bytes'])}), "
                f"step path blocked {s['ckpt_blocked_ms']:.0f} ms of "
                f"{s['ckpt_write_ms']:.0f} ms write time"
                + (f" · {s['ckpt_failures']} FAILED" if s["ckpt_failures"] else "")
            )
        tails = []
        for name, label in (
            ("Time/train_time", "train"),
            ("Time/env_interaction_time", "env"),
            ("Time/stage_h2d_time", "stage"),
            ("Time/plane_wait_time", "plane_wait"),
        ):
            pct = s.get("phase_percentiles", {}).get(name)
            if pct and pct.get("p95_ms") is not None:
                tails.append(f"{label} p50/p95 {pct['p50_ms']:.0f}/{pct['p95_ms']:.0f} ms")
        if tails:
            lines.append("  tails: " + " · ".join(tails))
        if s.get("learn_warnings") or s.get("learn_criticals"):
            lines.append(
                f"  learning health: {s.get('learn_warnings', 0)} warning(s) · "
                f"{s.get('learn_criticals', 0)} CRITICAL"
                + (
                    f" · grad_norm p95 {s['grad_norm_p95']:.3g}"
                    if s.get("grad_norm_p95") is not None
                    else ""
                )
            )
        if s.get("crashed"):
            lines.append(f"  CRASHED: {s.get('exception', '?')}")
        if s.get("flight_dumps"):
            lines.append(f"  flight recorder fired {s['flight_dumps']} time(s)")
        if self.summary_enabled and self.summary_path:
            lines.append(f"  written to {self.summary_path}")
        if "trace_file" in s:
            lines.append(f"  trace: {s['trace_file']}")
        lines.append("─" * 63)
        print("\n".join(lines), flush=True)


def setup_telemetry(cfg) -> Optional[Telemetry]:
    """Build and activate telemetry from a composed run config (or return
    None when ``metric.telemetry.enabled`` is off/absent)."""
    global _ACTIVE
    tcfg = {}
    try:
        tcfg = dict(cfg.metric.get("telemetry", {}) or {})
    except AttributeError:
        pass
    if not tcfg.get("enabled", False):
        _ACTIVE = None
        return None
    telemetry = Telemetry(tcfg)
    telemetry.start()
    _ACTIVE = telemetry
    return telemetry


def finalize_telemetry(
    print_summary: bool = True, error: Optional[BaseException] = None
) -> Optional[Dict[str, Any]]:
    """Finalize and deactivate the run telemetry (idempotent). ``error`` is
    the exception that ended the run (if any) — the summary then records
    ``"crashed": true`` plus the exception type alongside the partial
    counters, so a dead run's last telemetry is still evidence."""
    global _ACTIVE
    telemetry, _ACTIVE = _ACTIVE, None
    if telemetry is None:
        return None
    return telemetry.finalize(print_summary=print_summary, error=error)
