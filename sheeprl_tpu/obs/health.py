"""Run-health guards: non-finite losses and stalled player/trainer threads.

Two failure modes silently waste whole runs:

- a loss goes NaN/inf and training keeps burning accelerator-hours on
  garbage gradients;
- one side of a decoupled player↔trainer pair hangs (a wedged env worker, a
  dead remote device link) and the other side waits forever on the exchange
  queue with no output at all.

:class:`NonFiniteGuard` hooks the shared metric-aggregation path (every algo
logs its losses through :class:`~sheeprl_tpu.utils.metric.MetricAggregator`),
so any logged ``Loss/``-family value is checked the moment it reaches the
host — no extra device fetches. :class:`StallWatchdog` is a heartbeat
monitor: each thread beats once per loop iteration, a daemon thread flags
roles whose last beat is older than the timeout, warns (once per stall
episode — re-armed when the role recovers), counts the stall, and marks it
on the trace timeline.
"""

from __future__ import annotations

import math
import threading
import time
import warnings
from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

__all__ = ["NonFiniteGuard", "StallWatchdog"]


class NonFiniteGuard:
    """Callable ``(metric_name, value)`` guard for the aggregator hook."""

    def __init__(
        self,
        prefixes: Sequence[str] = ("Loss/", "Grads/"),
        raise_on_nonfinite: bool = False,
        counters=None,
        on_fire: Optional[Callable[[str, float], None]] = None,
    ):
        self.prefixes: Tuple[str, ...] = tuple(prefixes)
        self.raise_on_nonfinite = bool(raise_on_nonfinite)
        self._counters = counters
        self.on_fire = on_fire
        self._warned: set = set()
        self.fired = 0

    def __call__(self, name: str, value) -> None:
        if not name.startswith(self.prefixes):
            return
        try:
            v = float(np.asarray(value))
        except Exception:
            return
        if math.isfinite(v):
            return
        self.fired += 1
        if self._counters is not None:
            self._counters.add("nonfinite_metrics", 1)
        from sheeprl_tpu.obs.spans import get_tracer

        tracer = get_tracer()
        if tracer is not None:
            tracer.instant("nonfinite_metric", args={"metric": name, "value": str(v)})
        if self.on_fire is not None:
            try:
                self.on_fire(name, v)
            except Exception:
                pass
        if name not in self._warned:
            self._warned.add(name)
            warnings.warn(
                f"run-health: non-finite value {v} logged for '{name}' — the "
                "optimization has likely diverged (further occurrences of this "
                "metric are counted, not re-warned)",
                RuntimeWarning,
            )
        if self.raise_on_nonfinite:
            raise FloatingPointError(
                f"run-health: non-finite value {v} logged for '{name}' "
                "(metric.telemetry.health.raise_on_nonfinite=true)"
            )


class StallWatchdog:
    """Heartbeat watchdog for decoupled player↔trainer threads.

    Usage::

        watchdog = StallWatchdog(timeout_s=120.0)
        watchdog.register("player")
        watchdog.start()
        ...          # player thread: watchdog.beat("player") once per loop
        watchdog.stop()

    A role whose last beat is older than ``timeout_s`` is flagged exactly
    once per stall episode; a subsequent beat re-arms it. ``on_stall(role,
    age_s)`` runs on the watchdog thread (keep it cheap).

    Cold-start grace: until a role has beaten twice (= completed one full
    iteration), its threshold is ``timeout_s × warmup_factor`` — the first
    iteration legitimately contains the XLA compiles (20+ minutes through a
    tunneled link for a big program), and flagging those as stalls would make
    every cold run report a false positive.

    Backpressure: a role that is about to block on the player↔trainer
    exchange (a full queue, a condition wait) calls :meth:`pause` first —
    waiting for the *other* side is idleness, not a stall, and flagging it
    would blame the healthy role whenever its peer is merely slow. The next
    ``beat``/``resume`` re-arms monitoring. Both sides paused at once cannot
    mask a real deadlock of the exchange itself: the queue cannot be
    simultaneously full (blocking the producer) and empty (blocking the
    consumer), so a wedged side is always the unpaused one.
    """

    def __init__(
        self,
        timeout_s: float = 120.0,
        poll_s: Optional[float] = None,
        on_stall: Optional[Callable[[str, float], None]] = None,
        counters=None,
        warmup_factor: float = 10.0,
    ):
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s) if poll_s else max(min(self.timeout_s / 4.0, 5.0), 0.05)
        self.on_stall = on_stall
        self.warmup_factor = max(float(warmup_factor), 1.0)
        self._counters = counters
        self._beats: Dict[str, float] = {}
        self._beat_counts: Dict[str, int] = {}
        self._flagged: Dict[str, bool] = {}
        self._paused: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stall_events: list = []

    def register(self, role: str) -> None:
        with self._lock:
            self._beats[role] = time.monotonic()
            self._beat_counts[role] = 0
            self._flagged[role] = False

    def unregister(self, role: str) -> None:
        """A role that finished cleanly must stop being monitored."""
        with self._lock:
            self._beats.pop(role, None)
            self._beat_counts.pop(role, None)
            self._flagged.pop(role, None)
            self._paused.discard(role)

    def beat(self, role: str) -> None:
        with self._lock:
            self._beats[role] = time.monotonic()
            self._beat_counts[role] = self._beat_counts.get(role, 0) + 1
            self._flagged[role] = False
            self._paused.discard(role)

    def pause(self, role: str) -> None:
        """Suspend monitoring while the role blocks on its peer's exchange."""
        with self._lock:
            self._paused.add(role)

    def resume(self, role: str) -> None:
        with self._lock:
            self._beats[role] = time.monotonic()
            self._paused.discard(role)

    @property
    def stalled_roles(self) -> list:
        with self._lock:
            return [r for r, f in self._flagged.items() if f]

    def beat_ages(self) -> Dict[str, Dict[str, object]]:
        """Seconds since each role's last beat (the live snapshot reads
        this): ``{role: {"age_s", "paused", "beats"}}`` — a paused role is
        blocked on its peer's exchange, so its age is idleness, not delay."""
        now = time.monotonic()
        with self._lock:
            return {
                role: {
                    "age_s": round(now - last, 1),
                    "paused": role in self._paused,
                    "beats": self._beat_counts.get(role, 0),
                }
                for role, last in self._beats.items()
            }

    def check(self) -> None:
        """One watchdog pass (the poll thread calls this; tests may too)."""
        now = time.monotonic()
        stalled = []
        with self._lock:
            for role, last in self._beats.items():
                if role in self._paused:
                    continue
                age = now - last
                threshold = self.timeout_s * (
                    self.warmup_factor if self._beat_counts.get(role, 0) < 2 else 1.0
                )
                if age > threshold and not self._flagged[role]:
                    self._flagged[role] = True
                    stalled.append((role, age))
        for role, age in stalled:
            self.stall_events.append((role, age))
            if self._counters is not None:
                self._counters.add("stalls", 1)
            from sheeprl_tpu.obs.spans import get_tracer

            tracer = get_tracer()
            if tracer is not None:
                tracer.instant("stall", args={"role": role, "age_s": round(age, 1)})
            warnings.warn(
                f"run-health: '{role}' has not made progress for {age:.0f}s "
                f"(stall timeout {self.timeout_s:.0f}s) — the thread is likely "
                "wedged on an env worker, a device transfer, or the exchange "
                "queue",
                RuntimeWarning,
            )
            if self.on_stall is not None:
                try:
                    self.on_stall(role, age)
                except Exception:
                    pass

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.check()

    def start(self) -> None:
        if self.timeout_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="obs-stall-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
