"""Device/transfer/recompile counters.

Three measurement families, all host-side and sync-free:

- **host→HBM transfer accounting**: the staging paths
  (:func:`sheeprl_tpu.data.buffers.to_device`, the
  :class:`~sheeprl_tpu.data.device_ring.DeviceRingReplay` flush/upload, and
  the train loops' batch ``device_put``) report the numpy bytes they ship via
  :func:`add_h2d_bytes`. This measures exactly the path the round-5 verdict
  names as the architectural bottleneck (the 2–8 MB/s staging tunnel).
- **recompile accounting**: a process-wide ``jax.monitoring`` listener counts
  backend compiles (``/jax/core/compile/backend_compile_duration``) and
  persistent-cache hits, so a silent retrace storm — a shape or dtype leaking
  into a jitted signature — becomes a visible, logged number instead of a
  mystery slowdown.
- **device memory**: :func:`device_memory_stats` is the one
  ``Device.memory_stats()`` probe (generalizing the one-off check the device
  ring used for its allocation guard); :class:`DevicePoller` samples it on a
  background thread and tracks peak HBM use per run.

All counters are no-ops until :func:`install` is called (by
``setup_telemetry``) — the module-global pointer is ``None`` and every hot
path is a single attribute check.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import numpy as np

__all__ = [
    "Counters",
    "add_act_dispatches",
    "add_ckpt_blocked_ms",
    "add_ckpt_write",
    "add_env_async_steps",
    "add_env_degraded",
    "add_env_worker_restart",
    "add_h2d_bytes",
    "add_kernel_tier_degraded",
    "add_learn_fetch",
    "add_plane_player_restart",
    "add_plane_slabs",
    "add_prefetch",
    "add_replay_adoption",
    "add_replay_priority_updates",
    "add_ring_gather",
    "add_rollout_burst",
    "add_serve_batch",
    "add_serve_failed",
    "add_serve_requests",
    "add_serve_swap",
    "add_serve_traced",
    "add_slo_alert",
    "add_train_burst",
    "set_replay_shard_fill",
    "note_plane_policy_version",
    "device_memory_stats",
    "DevicePoller",
    "install",
    "installed",
    "set_compile_hook",
    "staged_device_put",
    "tree_nbytes",
]

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

_COUNTERS: Optional["Counters"] = None
_LISTENERS_REGISTERED = False
#: telemetry-installed callback fired on every backend compile (duration_s);
#: the flight recorder uses it to catch post-warmup recompile storms
_COMPILE_HOOK: Optional[Any] = None


def set_compile_hook(hook) -> None:
    """Install (or with ``None`` remove) the backend-compile callback."""
    global _COMPILE_HOOK
    _COMPILE_HOOK = hook


class Counters:
    """Thread-safe run counters (players/trainers/pollers all write here)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.h2d_bytes = 0
        self.h2d_transfers = 0
        self.recompiles = 0
        self.compile_secs = 0.0
        self.compile_cache_hits = 0
        self.nonfinite_metrics = 0
        self.stalls = 0
        self.ckpt_blocked_ms = 0.0
        self.ckpt_write_ms = 0.0
        self.ckpt_bytes = 0
        self.ckpt_saves = 0
        self.ckpt_failures = 0
        # replay staging (data/staging.py): ring gathers never re-cross the
        # host→HBM link; prefetch hits are bursts whose sampling + H2D ran
        # overlapped with the previous train burst, wait_ms the residue the
        # train thread still blocked on a not-yet-ready prefetched batch
        self.ring_gathers = 0
        self.prefetch_hits = 0
        self.prefetch_misses = 0
        self.prefetch_wait_ms = 0.0
        # async env execution plane (envs/vector): steps served by the
        # shared-memory worker pool, worker crash/hang restarts, and whether
        # the pool gave up and degraded to in-process sync stepping
        self.env_steps_async = 0
        self.env_worker_restarts = 0
        self.env_degraded_to_sync = 0
        # rollout engine (envs/rollout): `rollout_bursts` counts collection
        # bursts (one device dispatch each), `act_dispatches` counts policy
        # inference dispatches — per-step acting pays one per env step,
        # burst acting one per K steps, the jitted-scan jax backend one per
        # whole burst — and `env_steps_jax` counts env steps taken entirely
        # inside jit (pure-JAX envs, zero host involvement)
        self.rollout_bursts = 0
        self.act_dispatches = 0
        self.env_steps_jax = 0
        # train-burst engine (sheeprl_tpu/train): `train_bursts` counts
        # fused training bursts (one scanned device program per burst),
        # `train_dispatches` counts train-program device dispatches paid
        # for them (1 per fused burst, n_samples for a per-step loop), and
        # `train_burst_steps` counts the gradient steps those dispatches
        # covered — dispatches/steps is the measured
        # ``train_dispatches_per_step`` the bench evidence lines report
        self.train_bursts = 0
        self.train_dispatches = 0
        self.train_burst_steps = 0
        # actor–learner plane (sheeprl_tpu/plane): trajectory slabs received
        # by the learner over the shared-memory queues, the newest published
        # policy version (a gauge — max, not a sum), and player processes
        # respawned after a crash
        self.plane_traj_slabs = 0
        self.plane_policy_version = 0
        self.plane_player_restarts = 0
        # sharded replay plane (sheeprl_tpu/replay): priority rows rewritten
        # by the TD-priority writeback channel, slabs adopted straight into
        # the device ring (slab→HBM, no host-buffer hop), and a per-shard
        # fill gauge ({shard -> fraction}, set after every ingest)
        self.replay_priority_updates = 0
        self.replay_adoptions = 0
        self.replay_shard_fill: Dict[str, float] = {}
        # distributed comms (obs/dist/comms.py): host-level collectives
        # (fabric all-reduce/all-gather/broadcast/barrier) — total ops,
        # payload bytes, wall ms, plus a per-kind breakdown with the last
        # and best achieved wire GB/s (in-jit collectives are attributed by
        # the xplane comms parser instead, obs/prof)
        self.comms_ops = 0
        self.comms_bytes = 0
        self.comms_ms = 0.0
        self.comms_by_kind: Dict[str, Dict[str, Any]] = {}
        # parameter sharding (parallel/shard.py): per-device HBM footprint of
        # the model params and optimizer state under the active ShardingPlan
        # (gauges — set at placement, not summed; model_axis=1 runs record
        # the full replicated footprint), plus the model-axis size itself so
        # telemetry.json pins down what layout produced the numbers
        self.params_bytes_per_device = 0
        self.opt_state_bytes_per_device = 0
        self.model_axis_size = 1
        # fused-kernel subsystem (sheeprl_tpu/kernels): times a requested
        # tier was auto-degraded at agent-build time (pallas on a non-TPU
        # backend, or a family with no pallas kernel yet)
        self.kernel_tier_degraded = 0
        # evaluation subsystem (sheeprl_tpu/evals): service rounds and
        # episodes run in this process, plus in-run eval policy publications
        # (the async channel feeding the separate eval process — the eval
        # episodes themselves run over there, never in the trainer)
        self.eval_rounds = 0
        self.eval_episodes = 0
        self.inrun_eval_publishes = 0
        # policy-serving gateway (sheeprl_tpu/serve): act() requests accepted,
        # coalesced batch dispatches paid for them (requests/batches is the
        # coalescing factor), the rows those batches carried (rows/batches is
        # mean batch occupancy), batches the dispatcher could not launch by
        # their latency deadline (the device was still busy — the flight-
        # recorder trigger), in-place model hot-swaps, and requests that
        # failed (errored or abandoned at drain)
        self.serve_requests = 0
        self.serve_batches = 0
        self.serve_batch_rows = 0
        self.serve_deadline_misses = 0
        self.serve_swaps = 0
        self.serve_failed_requests = 0
        # request-path observability (obs/reqtrace + obs/slo): requests whose
        # six-stage span chain landed in the trace plane, and SLO burn-rate
        # alert firings (fast + slow pairs; clears are not counted)
        self.serve_traced_requests = 0
        self.slo_alerts_fired = 0
        # learning-health plane (sheeprl_tpu/obs/learn): graded sentinel
        # events plus the extra device→host probe pulls actually paid (the
        # "uninstrumented runs pay nothing" invariant is asserted on
        # learn_probe_fetches staying 0 when learn probes are off)
        self.learn_warnings = 0
        self.learn_criticals = 0
        self.learn_probe_fetches = 0

    def add_learn_event(self, warnings: int = 0, criticals: int = 0) -> None:
        with self._lock:
            self.learn_warnings += int(warnings)
            self.learn_criticals += int(criticals)

    def add(self, field: str, amount) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + amount)

    def add_comms(
        self, kind: str, payload_bytes: int, ms: float, gbps: Optional[float] = None
    ) -> None:
        """Record one host-level collective (obs/dist/comms.py)."""
        with self._lock:
            self.comms_ops += 1
            self.comms_bytes += int(payload_bytes)
            self.comms_ms += float(ms)
            k = self.comms_by_kind.setdefault(
                kind, {"ops": 0, "bytes": 0, "ms": 0.0, "last_gbps": None, "best_gbps": None}
            )
            k["ops"] += 1
            k["bytes"] += int(payload_bytes)
            k["ms"] += float(ms)
            if gbps is not None:
                k["last_gbps"] = round(gbps, 3)
                if k["best_gbps"] is None or gbps > k["best_gbps"]:
                    k["best_gbps"] = round(gbps, 3)

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "bytes_staged_h2d": self.h2d_bytes,
                "h2d_transfers": self.h2d_transfers,
                "recompiles": self.recompiles,
                "compile_secs": round(self.compile_secs, 3),
                "compile_cache_hits": self.compile_cache_hits,
                "nonfinite_metrics": self.nonfinite_metrics,
                "stalls": self.stalls,
                "ckpt_blocked_ms": round(self.ckpt_blocked_ms, 1),
                "ckpt_write_ms": round(self.ckpt_write_ms, 1),
                "ckpt_bytes": self.ckpt_bytes,
                "ckpt_saves": self.ckpt_saves,
                "ckpt_failures": self.ckpt_failures,
                "ring_gathers": self.ring_gathers,
                "prefetch_hits": self.prefetch_hits,
                "prefetch_misses": self.prefetch_misses,
                "prefetch_wait_ms": round(self.prefetch_wait_ms, 1),
                "env_steps_async": self.env_steps_async,
                "env_worker_restarts": self.env_worker_restarts,
                "env_degraded_to_sync": self.env_degraded_to_sync,
                "rollout_bursts": self.rollout_bursts,
                "act_dispatches": self.act_dispatches,
                "env_steps_jax": self.env_steps_jax,
                "train_bursts": self.train_bursts,
                "train_dispatches": self.train_dispatches,
                "train_burst_steps": self.train_burst_steps,
                "plane_traj_slabs": self.plane_traj_slabs,
                "plane_policy_version": self.plane_policy_version,
                "plane_player_restarts": self.plane_player_restarts,
                "replay_priority_updates": self.replay_priority_updates,
                "replay_adoptions": self.replay_adoptions,
                "replay_shard_fill": dict(self.replay_shard_fill),
                "params_bytes_per_device": self.params_bytes_per_device,
                "opt_state_bytes_per_device": self.opt_state_bytes_per_device,
                "model_axis_size": self.model_axis_size,
                "kernel_tier_degraded": self.kernel_tier_degraded,
                "eval_rounds": self.eval_rounds,
                "eval_episodes": self.eval_episodes,
                "inrun_eval_publishes": self.inrun_eval_publishes,
                "serve_requests": self.serve_requests,
                "serve_batches": self.serve_batches,
                "serve_batch_rows": self.serve_batch_rows,
                "serve_deadline_misses": self.serve_deadline_misses,
                "serve_swaps": self.serve_swaps,
                "serve_failed_requests": self.serve_failed_requests,
                "serve_traced_requests": self.serve_traced_requests,
                "slo_alerts_fired": self.slo_alerts_fired,
                "learn_warnings": self.learn_warnings,
                "learn_criticals": self.learn_criticals,
                "learn_probe_fetches": self.learn_probe_fetches,
                "comms_ops": self.comms_ops,
                "comms_bytes": self.comms_bytes,
                "comms_ms": round(self.comms_ms, 3),
                "comms": {
                    kind: {**v, "ms": round(v["ms"], 3)}
                    for kind, v in sorted(self.comms_by_kind.items())
                },
            }


def install(counters: Optional["Counters"]) -> None:
    """Activate (or with ``None`` deactivate) the run counters."""
    global _COUNTERS
    _COUNTERS = counters
    if counters is not None:
        _ensure_jax_listeners()


def installed() -> Optional["Counters"]:
    return _COUNTERS


# -- transfer accounting ----------------------------------------------------


def tree_nbytes(tree: Any) -> int:
    """Total bytes of the *host* (numpy) leaves of a pytree.

    Device-resident jax Arrays are skipped — reading their size is free, but
    they are not about to cross the host→HBM link again, and forcing them
    through numpy would add the device sync this module exists to avoid.
    """
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, np.ndarray):
            total += leaf.nbytes
        elif isinstance(leaf, (np.generic, bytes)):
            total += np.asarray(leaf).nbytes if isinstance(leaf, np.generic) else len(leaf)
    return total


def add_h2d_bytes(nbytes: int, transfers: int = 1) -> None:
    """Record ``nbytes`` staged host→device (no-op when telemetry is off)."""
    c = _COUNTERS
    if c is not None and nbytes:
        with c._lock:
            c.h2d_bytes += int(nbytes)
            c.h2d_transfers += transfers


def count_h2d(tree: Any) -> None:
    """Record the host bytes of ``tree`` as one staged transfer.

    The size walk itself is skipped when telemetry is off, so hot loops can
    call this unconditionally.
    """
    if _COUNTERS is not None:
        add_h2d_bytes(tree_nbytes(tree))


def staged_device_put(data: Any, device: Any):
    """``jax.device_put`` wrapped in the host→HBM staging span + byte count.

    The span measures the *dispatch* of the (async) transfer — on a local
    device that is approximately the copy itself; on a remote-attached link
    the tail of the transfer overlaps the caller's next work, which is the
    point. Byte accounting is exact either way.
    """
    import jax

    from sheeprl_tpu.obs.spans import span

    nbytes = tree_nbytes(data) if _COUNTERS is not None else 0
    with span("Time/stage_h2d_time", phase="stage_h2d"):
        out = jax.device_put(data, device)
    add_h2d_bytes(nbytes)
    return out


# -- replay staging accounting ----------------------------------------------


def add_ring_gather(n: int = 1) -> None:
    """Record ``n`` device-ring batch gathers (no host→HBM batch upload)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.ring_gathers += n


def add_prefetch(hit: bool, wait_ms: float = 0.0) -> None:
    """Record one prefetch-pipeline burst: a *hit* means the batch was
    sampled + staged while the previous train burst ran (``wait_ms`` is the
    residue the caller still blocked for); a *miss* means it was produced
    synchronously (cold start or a changed burst spec)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            if hit:
                c.prefetch_hits += 1
            else:
                c.prefetch_misses += 1
            c.prefetch_wait_ms += float(wait_ms)


# -- async env execution accounting ------------------------------------------


def add_env_async_steps(n: int) -> None:
    """Record ``n`` env steps served by the async shared-memory worker pool."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.env_steps_async += int(n)


def add_env_worker_restart(n: int = 1) -> None:
    """Record ``n`` env-worker restarts (crash or hang past the timeout)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.env_worker_restarts += int(n)


def add_env_degraded(n: int = 1) -> None:
    """Record the async env pool exhausting its restart budget and degrading
    to in-process sync stepping."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.env_degraded_to_sync += int(n)


# -- rollout engine accounting ------------------------------------------------


def add_rollout_burst(act_dispatches: int = 1, jax_steps: int = 0) -> None:
    """Record one collection burst: ``act_dispatches`` policy inference
    dispatches were paid for it (1 for a jitted burst, K for a per-step
    loop of K acts) and ``jax_steps`` env steps ran entirely inside jit."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.rollout_bursts += 1
            c.act_dispatches += int(act_dispatches)
            c.env_steps_jax += int(jax_steps)


def add_act_dispatches(n: int = 1) -> None:
    """Record ``n`` standalone policy inference dispatches (per-step acting
    paths not yet routed through a rollout burst)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.act_dispatches += int(n)


# -- train-burst engine accounting --------------------------------------------


def add_train_burst(steps: int = 0, dispatches: int = 1) -> None:
    """Record one training burst: ``steps`` gradient steps were trained
    through ``dispatches`` train-program device dispatches (1 for the fused
    scan, ``steps`` for the per-step reference loop)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.train_bursts += 1
            c.train_dispatches += int(dispatches)
            c.train_burst_steps += int(steps)


def add_learn_fetch(n: int = 1) -> None:
    """Record one learn-probe device→host pull (obs/learn.observe_probes)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.learn_probe_fetches += int(n)


# -- parameter-sharding accounting -------------------------------------------


def set_shard_footprint(
    params_bytes_per_device: int,
    opt_state_bytes_per_device: int,
    model_axis_size: int = 1,
) -> None:
    """Record the per-device HBM footprint of params/optimizer state under
    the active sharding layout (gauges, set once at placement — a replicated
    run records the full tree size with ``model_axis_size=1``)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.params_bytes_per_device = int(params_bytes_per_device)
            c.opt_state_bytes_per_device = int(opt_state_bytes_per_device)
            c.model_axis_size = int(model_axis_size)


# -- actor–learner plane accounting ------------------------------------------


def add_plane_slabs(n: int = 1) -> None:
    """Record ``n`` trajectory slabs received from player processes."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.plane_traj_slabs += int(n)


def note_plane_policy_version(version: int) -> None:
    """Record the newest published policy version (monotone gauge)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.plane_policy_version = max(c.plane_policy_version, int(version))


def add_plane_player_restart(n: int = 1) -> None:
    """Record ``n`` player-process respawns (crash within the restart budget)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.plane_player_restarts += int(n)


# -- sharded replay plane accounting -----------------------------------------


def add_replay_priority_updates(n: int = 1) -> None:
    """Record ``n`` priority rows rewritten by the TD-priority writeback
    channel (sheeprl_tpu/replay/strategies.py)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.replay_priority_updates += int(n)


def add_replay_adoption(n: int = 1) -> None:
    """Record ``n`` slabs adopted straight into the device ring
    (``DeviceRingTransitions.adopt_slab`` — no host-buffer hop)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.replay_adoptions += int(n)


def set_replay_shard_fill(fills: Dict[str, float]) -> None:
    """Record the per-shard fill gauge (fraction of ring capacity holding
    data, keyed by shard index as a string)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.replay_shard_fill.update({str(k): float(v) for k, v in fills.items()})


def add_kernel_tier_degraded(n: int = 1) -> None:
    """Record ``n`` fused-kernel tier auto-degrades (kernels/registry.py)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.kernel_tier_degraded += int(n)


# -- checkpoint accounting --------------------------------------------------


def add_ckpt_blocked_ms(ms: float) -> None:
    """Record wall milliseconds a train step spent blocked on a checkpoint
    (host snapshot + waiting out the previous in-flight save)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.ckpt_blocked_ms += float(ms)


def add_ckpt_write(ms: float, nbytes: int, failed: bool = False) -> None:
    """Record one checkpoint write (writer-thread time + bytes landed)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.ckpt_write_ms += float(ms)
            c.ckpt_bytes += int(nbytes)
            if failed:
                c.ckpt_failures += 1
            else:
                c.ckpt_saves += 1


# -- evaluation accounting --------------------------------------------------


def add_eval_rounds(n: int = 1) -> None:
    """Record ``n`` eval-service rounds run in this process (evals/service)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.eval_rounds += int(n)


def add_eval_episodes(n: int) -> None:
    """Record ``n`` frozen-policy eval episodes completed (evals/service)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.eval_episodes += int(n)


def add_inrun_eval_publishes(n: int = 1) -> None:
    """Record ``n`` in-run eval policy publications (evals/inrun)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.inrun_eval_publishes += int(n)


# -- policy-serving gateway accounting ----------------------------------------


def add_serve_requests(n: int = 1) -> None:
    """Record ``n`` act() requests accepted by the gateway (serve/batcher)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.serve_requests += int(n)


def add_serve_batch(rows: int, deadline_miss: bool = False) -> None:
    """Record one coalesced batch dispatch carrying ``rows`` requests;
    ``deadline_miss`` marks a batch the dispatcher launched *after* its
    latency deadline had already expired (device busy, not a partial fill)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.serve_batches += 1
            c.serve_batch_rows += int(rows)
            if deadline_miss:
                c.serve_deadline_misses += 1


def add_serve_swap(n: int = 1) -> None:
    """Record ``n`` in-place gateway model hot-swaps (serve/model)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.serve_swaps += int(n)


def add_serve_failed(n: int = 1) -> None:
    """Record ``n`` failed serve requests (dispatch error or drain abandon)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.serve_failed_requests += int(n)


def add_serve_traced(n: int = 1) -> None:
    """Record ``n`` requests whose span chain landed in the trace plane."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.serve_traced_requests += int(n)


def add_slo_alert(n: int = 1) -> None:
    """Record ``n`` SLO burn-rate alert firings (obs/slo)."""
    c = _COUNTERS
    if c is not None:
        with c._lock:
            c.slo_alerts_fired += int(n)


# -- recompile accounting ---------------------------------------------------


def _on_event_duration(event: str, duration: float, **_kw) -> None:
    c = _COUNTERS
    if c is not None and event == _BACKEND_COMPILE_EVENT:
        with c._lock:
            c.recompiles += 1
            c.compile_secs += float(duration)
        hook = _COMPILE_HOOK
        if hook is not None:
            try:
                hook(float(duration))
            except Exception:
                pass


def _on_event(event: str, **_kw) -> None:
    c = _COUNTERS
    if c is not None and event == _CACHE_HIT_EVENT:
        with c._lock:
            c.compile_cache_hits += 1


def _ensure_jax_listeners() -> None:
    """Register the jax.monitoring listeners once per process.

    jax offers no targeted unregister, so the listeners live for the process
    and forward to whichever counters are currently installed (no-op when
    telemetry is off).
    """
    global _LISTENERS_REGISTERED
    if _LISTENERS_REGISTERED:
        return
    import jax

    jax.monitoring.register_event_duration_secs_listener(_on_event_duration)
    jax.monitoring.register_event_listener(_on_event)
    _LISTENERS_REGISTERED = True


# -- device memory ----------------------------------------------------------


def device_memory_stats(device: Any) -> Optional[Dict[str, Any]]:
    """``device.memory_stats()`` or None (CPU backends / unsupported runtimes)."""
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    return stats or None


class DevicePoller:
    """Background sampler of per-device memory stats.

    Tracks the run's peak HBM use (``peak_bytes_in_use`` where the runtime
    reports it, ``bytes_in_use`` otherwise) and, when a tracer is active,
    emits one counter event per sample so HBM occupancy is plottable on the
    same timeline as the phase spans. Zero interaction with the dispatch
    path: ``memory_stats`` is a local runtime query, not a device program.
    """

    def __init__(self, interval_s: float = 5.0, devices: Optional[list] = None):
        self.interval_s = float(interval_s)
        self._devices = devices
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.peak_hbm_bytes = 0
        self.hbm_bytes_limit = 0
        self.samples = 0

    def _resolve_devices(self) -> list:
        if self._devices is None:
            import jax

            self._devices = list(jax.local_devices())
        return self._devices

    def sample_once(self) -> None:
        from sheeprl_tpu.obs.spans import get_tracer

        in_use: Dict[str, float] = {}
        peak = 0
        limit = 0
        for dev in self._resolve_devices():
            stats = device_memory_stats(dev)
            if not stats:
                continue
            used = int(stats.get("bytes_in_use", 0))
            peak = max(peak, int(stats.get("peak_bytes_in_use", used)))
            limit = max(limit, int(stats.get("bytes_limit", 0)))
            in_use[str(dev.id)] = used
        with self._lock:
            self.samples += 1
            self.peak_hbm_bytes = max(self.peak_hbm_bytes, peak)
            self.hbm_bytes_limit = max(self.hbm_bytes_limit, limit)
        tracer = get_tracer()
        if tracer is not None and in_use:
            tracer.counter("hbm_bytes_in_use", in_use)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample_once()

    def start(self) -> None:
        if self.interval_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="obs-device-poller", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        # even a run shorter than one interval gets a final sample
        self.sample_once()

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "peak_hbm_bytes": self.peak_hbm_bytes,
                "hbm_bytes_limit": self.hbm_bytes_limit,
                "hbm_samples": self.samples,
            }
