"""Learning-health observability: in-jit dynamics probes + the divergence
sentinel (howto/learning_health.md).

The machine-health planes (spans, counters, roofline, staleness) say nothing
about whether the *learning* is healthy: a diverging run looks identical to a
converging one in telemetry.json until the NaN guard fires, long after the
damage is done. This package closes that gap with two pieces:

- :mod:`~sheeprl_tpu.obs.learn.probes` — ``learn_probes(...)``, a helper
  computed INSIDE the jitted train program (global/per-module grad norms,
  update-to-weight ratio, param norm, clip-fraction, non-finite leaf count)
  returned as a flat dict of f32 scalars under the ``learn/`` key prefix.
  ``train/burst.py`` stacks those keys across the scanned burst regardless of
  the family's ``metric_mode`` and feeds them to the sentinel behind the
  fetch cadence — at most one extra scalar pull per burst, zero extra
  dispatches. The fused programs (SAC/PPO/...) stack them through their
  existing ``lax.scan`` and call :func:`observe_probes` host-side.
- :mod:`~sheeprl_tpu.obs.learn.sentinel` — streaming-histogram baselines per
  probe (obs/hist.py) with graded events: ``warn`` on grad-norm z-score
  excursions or update-ratio collapse, ``critical`` on sustained explosion
  (before any NaN lands) or non-finite gradients — each firing the flight
  recorder's ``learn_divergence`` trigger and landing in
  telemetry.json/live.json/Prometheus.

Like every other obs plane the module-global is installed by
``setup_telemetry`` and everything is a no-op without it: with the sentinel
uninstalled, :func:`probes_enabled` is False, so the probe computation is
never even *compiled* into the train program and instrumented runs stay
bitwise identical to uninstrumented ones.
"""

from __future__ import annotations

from typing import Any, Optional

from sheeprl_tpu.obs.learn.probes import LEARN_PREFIX, learn_probes, split_probes
from sheeprl_tpu.obs.learn.sentinel import LearnSentinel

__all__ = [
    "LEARN_PREFIX",
    "LearnSentinel",
    "install",
    "installed",
    "learn_probes",
    "observe_probes",
    "probes_enabled",
    "split_probes",
]

_SENTINEL: Optional[LearnSentinel] = None


def install(sentinel: Optional[LearnSentinel]) -> None:
    """Activate (or with ``None`` deactivate) the run's learn sentinel."""
    global _SENTINEL
    _SENTINEL = sentinel


def installed() -> Optional[LearnSentinel]:
    return _SENTINEL


def probes_enabled(cfg: Any = None) -> bool:
    """Should the train program being built compute learn probes?

    True exactly when a sentinel is installed (telemetry on AND
    ``metric.telemetry.learn.enabled``) — the build-time gate every algo
    checks, so probes-off programs carry zero probe ops. ``cfg`` is accepted
    for call-site symmetry; the installed sentinel is the single source of
    truth.
    """
    return _SENTINEL is not None


def observe_probes(probes: Any, step: Optional[int] = None) -> None:
    """Feed one burst's stacked probe pytree to the sentinel (host side).

    ``probes`` may be device arrays — they are pulled with ONE ``device_get``
    only when the sentinel's ``every_n_bursts`` cadence is due (the
    ``learn_probe_fetches`` counter records every pull). No-op when probes
    are None (program built without them) or no sentinel is installed.
    """
    s = _SENTINEL
    if s is None or probes is None:
        return
    if not s.due_burst():
        return
    import jax

    from sheeprl_tpu.obs.counters import add_learn_fetch

    vals = jax.device_get(probes)
    add_learn_fetch()
    s.observe(vals, step=step)
