"""In-jit training-dynamics probes.

:func:`learn_probes` runs INSIDE the jitted gradient step, over intermediates
the step already has in hand (grads, params, optimizer updates, losses) — it
adds a handful of reductions and zero extra dispatches. The result is a flat
``{"learn/...": f32 scalar}`` dict designed to ride the family's existing
metric pytree: ``train/burst.py`` recognizes the ``learn/`` prefix and
stack-accumulates those keys across the burst whatever the metric mode, and
the fused programs stack them through their own ``lax.scan`` ys.

Probe definitions (howto/learning_health.md):

- ``learn/grad_norm`` — global L2 norm over every module's gradients;
- ``learn/grad_norm/<module>`` — per-top-level-module L2 grad norms;
- ``learn/param_norm`` — global L2 norm of the parameters *entering* the
  step (the sentinel derives param-norm drift host-side from successive
  samples);
- ``learn/update_ratio`` — ‖updates‖ / (‖params‖ + eps), the update-to-weight
  ratio (the classic ~1e-3 rule of thumb; collapse → dead optimizer,
  explosion → LR too hot);
- ``learn/clip_frac`` — the fraction of clip-configured modules whose raw
  grad norm exceeded their ``optax.clip_by_global_norm`` threshold this step.
  The threshold is SURFACED from the optimizer factory
  (``utils.optim.clip_norm_of``), not recomputed from config;
- ``learn/nonfinite`` — count of gradient leaves (plus loss entries)
  containing any non-finite value: the earliest possible NaN signal, one
  full metric-fetch cadence ahead of the aggregator-level NonFiniteGuard.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional, Tuple

__all__ = ["LEARN_PREFIX", "learn_probes", "split_probes"]

#: metric-dict key prefix the burst engine stack-accumulates unconditionally
LEARN_PREFIX = "learn/"

#: update-ratio denominator guard (a zero-norm param tree is init-only)
_EPS = 1e-12


def _sq_norm(tree: Any):
    """Sum of squares over every leaf of a pytree (f32 scalar)."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    return sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)


def _nonfinite_leaves(tree: Any):
    """Count of leaves with ANY non-finite entry (f32 scalar)."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    return sum(
        jnp.any(~jnp.isfinite(x)).astype(jnp.float32) for x in leaves
    )


def learn_probes(
    grads: Mapping[str, Any],
    params: Optional[Mapping[str, Any]] = None,
    updates: Optional[Mapping[str, Any]] = None,
    losses: Any = None,
    *,
    clip_norms: Optional[Mapping[str, Optional[float]]] = None,
) -> Dict[str, Any]:
    """Compute the learning-dynamics probe dict inside a jitted step.

    ``grads``/``params``/``updates`` are dicts keyed by top-level module name
    (``{"world_model": ..., "actor": ...}``); ``params`` are the parameters
    the step STARTED from, ``updates`` the optax update trees actually
    applied. ``losses`` is any pytree of loss scalars (folded into the
    non-finite count). ``clip_norms`` maps module → ``clip_by_global_norm``
    threshold (None/absent: module not clipped; from
    ``utils.optim.clip_norm_of``).

    Returns a flat ``{"learn/...": f32 scalar}`` dict — merge it into the
    step's metric dict (burst families) or return it as a scan y (fused
    programs).
    """
    import jax.numpy as jnp

    grads = dict(grads)
    clip_norms = dict(clip_norms or {})
    out: Dict[str, Any] = {}

    grad_sq = jnp.float32(0.0)
    nonfinite = _nonfinite_leaves(losses) if losses is not None else jnp.float32(0.0)
    clip_flags = []
    for name in sorted(grads):
        sq = _sq_norm(grads[name])
        gnorm = jnp.sqrt(sq)
        grad_sq = grad_sq + sq
        out[f"{LEARN_PREFIX}grad_norm/{name}"] = gnorm
        nonfinite = nonfinite + _nonfinite_leaves(grads[name])
        clip = clip_norms.get(name)
        if clip is not None and clip > 0:
            clip_flags.append((gnorm > jnp.float32(clip)).astype(jnp.float32))
    out[f"{LEARN_PREFIX}grad_norm"] = jnp.sqrt(grad_sq)
    out[f"{LEARN_PREFIX}clip_frac"] = (
        sum(clip_flags) / jnp.float32(len(clip_flags))
        if clip_flags
        else jnp.float32(0.0)
    )
    out[f"{LEARN_PREFIX}nonfinite"] = nonfinite

    if params is not None:
        param_norm = jnp.sqrt(sum(_sq_norm(t) for t in dict(params).values()))
        out[f"{LEARN_PREFIX}param_norm"] = param_norm
        if updates is not None:
            update_norm = jnp.sqrt(sum(_sq_norm(t) for t in dict(updates).values()))
            out[f"{LEARN_PREFIX}update_ratio"] = update_norm / (param_norm + _EPS)
    return out


def split_probes(metrics: Any) -> Tuple[Any, Optional[Dict[str, Any]]]:
    """Partition a metric dict into ``(rest, learn_subtree_or_None)``.

    Non-dict metric pytrees pass through untouched (fused programs hand
    their probes around separately).
    """
    if not isinstance(metrics, dict):
        return metrics, None
    learn = {k: v for k, v in metrics.items() if k.startswith(LEARN_PREFIX)}
    if not learn:
        return metrics, None
    rest = {k: v for k, v in metrics.items() if not k.startswith(LEARN_PREFIX)}
    return rest, learn
