"""Divergence early-warning over the learn probes.

The sentinel is the host half of the learning-health plane: it receives each
burst's stacked probe samples (``obs.learn.observe_probes``), keeps a
streaming-histogram baseline (obs/hist.py) plus a running mean/std per probe
in log10 space (training dynamics are multiplicative — a 10x grad-norm jump
is the unit of interest, not +10), and fires graded events:

- ``warn`` — a grad-norm sample's z-score exceeds ``warn_z``, or the
  update-to-weight ratio collapses below ``-warn_z`` (a dead optimizer looks
  *quieter*, not louder);
- ``critical`` — ``critical_streak`` consecutive grad-norm samples above
  ``critical_z`` (sustained explosion: fires BEFORE the first NaN reaches
  the loss), or any non-finite gradient leaf / non-finite logged metric
  (the NonFiniteGuard's terminal stage — ``Telemetry._on_nonfinite`` routes
  into :meth:`LearnSentinel.on_nonfinite`).

Every event triggers the flight recorder (``learn_divergence`` reason, rate
limits apply), bumps the ``learn_warnings``/``learn_criticals`` counters, and
lands timestamped in the summary's ``learn.events`` — the acceptance
ordering (critical BEFORE first non-finite) is checked against
``learn.first_nonfinite_ts``.

Anomalous samples (|z| > critical_z) are NOT absorbed into the baseline:
a baseline that chases the explosion would re-arm mid-divergence and the
"sustained" criterion would never accumulate.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Mapping, Optional

from sheeprl_tpu.obs.hist import StreamingHist

__all__ = ["LearnSentinel"]

#: floor for log10 of a probe sample (an exactly-zero grad norm)
_LOG_FLOOR = -30.0
#: events kept for the summary (counters stay exact beyond this)
_MAX_EVENTS = 64


def _log10(value: float) -> float:
    return math.log10(value) if value > 0.0 else _LOG_FLOOR


class _Baseline:
    """Welford mean/var in log10 space plus the mergeable value histogram."""

    __slots__ = ("hist", "n", "mean", "m2", "last")

    def __init__(self):
        self.hist = StreamingHist()
        self.n = 0
        self.mean = 0.0
        self.m2 = 0.0
        self.last = 0.0

    def z(self, log_value: float) -> Optional[float]:
        if self.n < 2:
            return None
        var = self.m2 / (self.n - 1)
        std = math.sqrt(var) if var > 0 else 0.0
        # std floor of 0.05 decades (~12% relative): an ultra-flat baseline
        # would otherwise turn benign drift into huge z-scores — below it a
        # "4-sigma" excursion can be a rounding-level wiggle, never actionable
        std = max(std, 0.05)
        return (log_value - self.mean) / std

    def absorb(self, value: float, log_value: float) -> None:
        self.hist.record(value)
        self.n += 1
        delta = log_value - self.mean
        self.mean += delta / self.n
        self.m2 += delta * (log_value - self.mean)
        self.last = value


class LearnSentinel:
    """Graded divergence events over streaming probe baselines.

    ``cfg`` is the ``metric.telemetry.learn`` dict; ``counters`` the run's
    ``obs.counters.Counters``; ``flight`` the FlightRecorder (or None);
    ``step_source`` an optional zero-arg callable giving the current policy
    step for events observed without an explicit step.
    """

    def __init__(
        self,
        cfg: Optional[Mapping[str, Any]] = None,
        counters: Any = None,
        flight: Any = None,
        step_source: Any = None,
    ):
        cfg = dict(cfg or {})
        self.every_n_bursts = max(int(cfg.get("every_n_bursts", 1) or 1), 1)
        self.warn_z = float(cfg.get("warn_z", 4.0))
        self.critical_z = float(cfg.get("critical_z", 8.0))
        self.warmup = max(int(cfg.get("warmup", 20)), 2)
        self.critical_streak = max(int(cfg.get("critical_streak", 3)), 1)
        self._counters = counters
        self._flight = flight
        self._step_source = step_source
        self._lock = threading.Lock()
        self._baselines: Dict[str, _Baseline] = {}
        self._streaks: Dict[str, int] = {}
        self._bursts_seen = 0
        self.warnings = 0
        self.criticals = 0
        self.events: List[Dict[str, Any]] = []
        self.first_nonfinite_ts: Optional[float] = None

    # -- cadence ------------------------------------------------------------

    def due_burst(self) -> bool:
        """Advance the burst counter; True when this burst's probes should be
        pulled (``every_n_bursts`` cadence, first burst always due)."""
        with self._lock:
            self._bursts_seen += 1
            return (self._bursts_seen - 1) % self.every_n_bursts == 0

    # -- observation --------------------------------------------------------

    def observe(self, probes: Mapping[str, Any], step: Optional[int] = None) -> None:
        """Record one burst's probes — each value a scalar or a stacked
        ``[n]`` array of per-gradient-step samples (host numpy)."""
        import numpy as np

        if step is None and self._step_source is not None:
            try:
                step = int(self._step_source())
            except Exception:
                step = None
        with self._lock:
            for key in sorted(probes):
                vals = np.ravel(np.asarray(probes[key], dtype=np.float64))
                for v in vals:
                    self._observe_one(key, float(v), step)

    def _observe_one(self, key: str, value: float, step: Optional[int]) -> None:
        if key.endswith("/nonfinite") or key == "learn/nonfinite":
            if value > 0:
                self._fire(
                    "critical", key, value, None, step, reason="nonfinite_grads"
                )
                self._note_nonfinite()
            return
        if not math.isfinite(value):
            self._fire("critical", key, value, None, step, reason="nonfinite_probe")
            self._note_nonfinite()
            return
        base = self._baselines.get(key)
        if base is None:
            base = self._baselines[key] = _Baseline()
        lv = _log10(value)
        z = base.z(lv) if base.n >= self.warmup else None
        is_grad = key.startswith("learn/grad_norm")
        is_ratio = key == "learn/update_ratio"
        anomalous = False
        if z is not None:
            if is_grad and z > self.critical_z:
                anomalous = True
                streak = self._streaks.get(key, 0) + 1
                self._streaks[key] = streak
                if streak >= self.critical_streak:
                    self._fire(
                        "critical", key, value, z, step, reason="sustained_explosion"
                    )
                    self._streaks[key] = 0
                else:
                    self._fire("warn", key, value, z, step, reason="grad_norm_excursion")
            elif is_grad and z > self.warn_z:
                self._streaks[key] = 0
                self._fire("warn", key, value, z, step, reason="grad_norm_excursion")
            elif is_ratio and z < -self.warn_z:
                self._fire("warn", key, value, z, step, reason="update_ratio_collapse")
            elif is_grad:
                self._streaks[key] = 0
        if not anomalous:
            base.absorb(value, lv)

    def on_nonfinite(self, name: str, value: Any) -> None:
        """NonFiniteGuard terminal stage: a non-finite value reached the
        metric aggregator. Timestamps the first occurrence (the acceptance
        ordering reference) and records a critical event."""
        with self._lock:
            self._note_nonfinite()
            self._fire(
                "critical",
                f"metric:{name}",
                float("nan"),
                None,
                None,
                reason="nonfinite_metric",
            )

    def _note_nonfinite(self) -> None:
        if self.first_nonfinite_ts is None:
            self.first_nonfinite_ts = time.time()

    # -- events -------------------------------------------------------------

    def _fire(
        self,
        severity: str,
        probe: str,
        value: float,
        z: Optional[float],
        step: Optional[int],
        reason: str,
    ) -> None:
        event = {
            "severity": severity,
            "probe": probe,
            "reason": reason,
            "value": None if not math.isfinite(value) else round(value, 6),
            "z": round(z, 3) if z is not None else None,
            "step": step,
            "ts_unix": time.time(),
        }
        if severity == "critical":
            self.criticals += 1
        else:
            self.warnings += 1
        if len(self.events) < _MAX_EVENTS:
            self.events.append(event)
        if self._counters is not None:
            try:
                self._counters.add_learn_event(
                    warnings=1 if severity == "warn" else 0,
                    criticals=1 if severity == "critical" else 0,
                )
            except Exception:
                pass
        if self._flight is not None:
            try:
                self._flight.trigger("learn_divergence", dict(event))
            except Exception:
                # telemetry must never take the run down
                pass

    # -- reporting ----------------------------------------------------------

    def quantile(self, key: str, q: float) -> Optional[float]:
        with self._lock:
            base = self._baselines.get(key)
            return base.hist.quantile(q) if base is not None else None

    def summary(self) -> Dict[str, Any]:
        """The ``learn`` sub-dict of telemetry.json / live.json."""
        with self._lock:
            probes: Dict[str, Any] = {}
            for key in sorted(self._baselines):
                base = self._baselines[key]
                probes[key] = {
                    "n": base.n,
                    "last": round(base.last, 6),
                    "p50": base.hist.quantile(0.50),
                    "p95": base.hist.quantile(0.95),
                    "max": base.hist.max,
                }
            return {
                "warnings": self.warnings,
                "criticals": self.criticals,
                "bursts_observed": self._bursts_seen,
                "first_nonfinite_ts": self.first_nonfinite_ts,
                "events": list(self.events),
                "probes": probes,
            }
