"""Data-staleness lineage: sample age, policy lag, and queue-depth gauges.

Every actor–learner stack lives or dies by two distributions nobody was
measuring here: **how old is the data a gradient step consumes** (wall
seconds between a transition landing in replay and being drawn into a
batch) and **how stale is the policy that collected it** (published
versions behind the learner at collection time). This module owns both:

- trajectory rows are stamped when they enter the replay buffer
  (``ReplayBuffer.add`` — for plane runs the slab's *commit* timestamp is
  carried across the process boundary and consumed via
  :meth:`StalenessTracker.stamp_next_add`, so the age clock starts at
  collection, not at the learner-side copy);
- every sampling plan (``ReplayBuffer.plan_transitions`` /
  ``SequentialReplayBuffer.plan_starts`` — one chokepoint under both the
  host path and the device-ring planners) observes the ages of the rows it
  drew into the ``sample_age_s`` histogram, vectorized so a 10k-row burst
  plan costs one ``np.log2``, not 10k Python calls;
- the plane supervisor observes ``policy_lag_versions`` (last published
  version − the version that collected each received burst) per slab, and
  the slab/prefetch queues report depth gauges (last + max) so
  backpressure is a number.

Percentiles surface as the ``staleness`` section of ``telemetry.json`` /
``live.json`` (plus flat ``sample_age_p95_s`` for ``tools/bench_compare.py``)
and as ``sheeprl_sample_age_seconds{quantile=...}`` Prometheus series.
Installed by ``setup_telemetry``; with no tracker installed every hook is a
single global read.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from sheeprl_tpu.obs.hist import StreamingHist

__all__ = [
    "StalenessTracker",
    "install",
    "installed",
    "observe_policy_lag",
    "observe_sample_ages",
    "stamp_next_add",
    "note_queue_depth",
    "take_add_stamp",
]

_TRACKER: Optional["StalenessTracker"] = None


def install(tracker: Optional["StalenessTracker"]) -> None:
    """Activate (or with ``None`` deactivate) the run's staleness tracker."""
    global _TRACKER
    _TRACKER = tracker


def installed() -> Optional["StalenessTracker"]:
    return _TRACKER


class StalenessTracker:
    """Run-wide staleness state (thread-safe; shared by the learner loop,
    the prefetch worker, and the plane supervisor)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.sample_age = StreamingHist()
        self.policy_lag = StreamingHist()
        self._queues: Dict[str, Dict[str, float]] = {}
        self._pending_stamp: Optional[float] = None

    # -- add-time stamping ---------------------------------------------------

    def stamp_next_add(self, ts: float) -> None:
        """Override the timestamp of the next ``ReplayBuffer.add`` — the
        plane learner sets the slab's commit time here right before copying
        the slab rows in, so sample age is measured from collection."""
        with self._lock:
            self._pending_stamp = float(ts)

    def take_add_stamp(self) -> float:
        """The stamp for rows being added right now (one-shot override, else
        the current wall clock)."""
        with self._lock:
            ts, self._pending_stamp = self._pending_stamp, None
        return time.time() if ts is None else ts

    # -- observations --------------------------------------------------------

    def observe_sample_ages(self, ages_s: np.ndarray) -> None:
        """Record the ages (seconds) of one sampling plan's drawn rows."""
        self.sample_age.record_many(ages_s)

    def observe_policy_lag(self, lag_versions: int, n: int = 1) -> None:
        """Record the version lag of one received trajectory burst."""
        lag = max(int(lag_versions), 0)
        for _ in range(max(int(n), 1)):
            self.policy_lag.record(float(lag))

    def note_queue_depth(self, name: str, depth: Optional[int]) -> None:
        """Update a queue-depth gauge (``last`` + running ``max``)."""
        if depth is None:
            return
        depth = int(depth)
        with self._lock:
            g = self._queues.setdefault(name, {"last": 0, "max": 0, "samples": 0})
            g["last"] = depth
            g["max"] = max(g["max"], depth)
            g["samples"] += 1

    # -- reporting -----------------------------------------------------------

    @staticmethod
    def _pcts(hist: StreamingHist, unit: str, digits: int = 4) -> Dict[str, Any]:
        def q(p):
            v = hist.quantile(p)
            return None if v is None else round(v, digits)

        return {
            "count": hist.n,
            f"p50_{unit}": q(0.50),
            f"p95_{unit}": q(0.95),
            f"p99_{unit}": q(0.99),
            f"max_{unit}": round(hist.max, digits),
        }

    def summary(self) -> Optional[Dict[str, Any]]:
        """The ``staleness`` section of the run summary, or None when
        nothing was ever observed (coupled single-process runs that never
        sampled replay stay clean)."""
        if self.sample_age.n == 0 and self.policy_lag.n == 0 and not self._queues:
            return None
        out: Dict[str, Any] = {}
        if self.sample_age.n:
            out["sample_age_s"] = self._pcts(self.sample_age, "s")
        if self.policy_lag.n:
            # lags are small integers; 2 digits keeps the geometric-mid
            # bucket estimates readable
            out["policy_lag_versions"] = self._pcts(self.policy_lag, "v", digits=2)
        if self._queues:
            with self._lock:
                out["queue_depth"] = {k: dict(v) for k, v in self._queues.items()}
        return out

    # -- sidecar serialization ----------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            queues = {k: dict(v) for k, v in self._queues.items()}
        return {
            "sample_age": self.sample_age.to_dict(),
            "policy_lag": self.policy_lag.to_dict(),
            "queues": queues,
        }

    def merge_dict(self, dumped: Dict[str, Any]) -> None:
        """Merge another process's tracker dump (exact — same log-bucket
        merge as the phase histograms)."""
        if not isinstance(dumped, dict):
            return
        if dumped.get("sample_age"):
            self.sample_age.merge(StreamingHist.from_dict(dumped["sample_age"]))
        if dumped.get("policy_lag"):
            self.policy_lag.merge(StreamingHist.from_dict(dumped["policy_lag"]))
        for name, g in (dumped.get("queues") or {}).items():
            with self._lock:
                mine = self._queues.setdefault(name, {"last": 0, "max": 0, "samples": 0})
                mine["max"] = max(mine["max"], int(g.get("max", 0)))
                mine["samples"] += int(g.get("samples", 0))
                # "last" keeps the local value — a remote last is not newer


# -- module-level hooks (no-ops when telemetry is off) ------------------------


def observe_sample_ages(ages_s: np.ndarray) -> None:
    t = _TRACKER
    if t is not None:
        t.observe_sample_ages(ages_s)


def observe_policy_lag(lag_versions: int, n: int = 1) -> None:
    t = _TRACKER
    if t is not None:
        t.observe_policy_lag(lag_versions, n)


def stamp_next_add(ts: float) -> None:
    t = _TRACKER
    if t is not None:
        t.stamp_next_add(ts)


def take_add_stamp() -> Optional[float]:
    """The add-time stamp, or None when no tracker is installed (callers
    then skip the stamping array entirely)."""
    t = _TRACKER
    return t.take_add_stamp() if t is not None else None


def note_queue_depth(name: str, depth: Optional[int]) -> None:
    t = _TRACKER
    if t is not None:
        t.note_queue_depth(name, depth)
