"""Cross-process telemetry aggregation: one merged view, per-source truth.

Sources and how they reach rank 0 / the learner:

==================  =========================================================
source              transport
==================  =========================================================
``rank<k>``         ``jax.distributed`` ranks > 0 write a full summary
                    sidecar (``telemetry/sidecar_rank<k>.json``) at finalize;
                    rank 0 SUMS their counters into the merged totals (nothing
                    else ever folds them).
``player<k>``       plane player processes push cumulative counter snapshots
                    over the supervisor's event queue while running (the
                    supervisor folds *deltas* of the shared counter subset and
                    publishes the raw snapshot here), and write a final
                    sidecar (``telemetry/sidecar_player<k>.json``) at exit —
                    breakdown only, their shared counters are already folded.
``envpool*``        env-worker pools publish per-worker step/busy/restart
                    stats at close — in the learner process straight into
                    this registry (+ a sidecar), inside a player process into
                    the player's local registry, embedded in its sidecar and
                    lifted to ``player<k>/envpool*`` here.
==================  =========================================================

``Telemetry.summary()`` attaches the live registry as ``sources`` (so
``live.json`` shows the breakdown mid-run) and ``Telemetry.finalize`` calls
:func:`merge_into_summary` for the durable merge: sidecars win over
in-memory snapshots (they are final), rank counters are summed exactly once,
and a torn/unreadable sidecar degrades to a ``{"torn": true}`` entry instead
of breaking finalize. The merge is deterministic: sources sort by name,
summing is plain integer addition.
"""

from __future__ import annotations

import glob
import json
import os
import re
import threading
from typing import Any, Dict, Optional

__all__ = [
    "clear_sources",
    "merge_into_summary",
    "publish_source",
    "read_sidecars",
    "sidecar_path",
    "source_snapshots",
    "write_sidecar",
]

_LOCK = threading.Lock()
_SOURCES: Dict[str, Dict[str, Any]] = {}

_SIDECAR_RE = re.compile(r"sidecar_([A-Za-z0-9_.\-]+)\.json$")

#: counter fields summed across rank sidecars into the merged totals (the
#: plain summable subset of Counters.as_dict — gauges and rates excluded)
SUMMED_RANK_COUNTERS = (
    "bytes_staged_h2d",
    "h2d_transfers",
    "recompiles",
    "compile_cache_hits",
    "nonfinite_metrics",
    "stalls",
    "ckpt_bytes",
    "ckpt_saves",
    "ckpt_failures",
    "ring_gathers",
    "prefetch_hits",
    "prefetch_misses",
    "env_steps_async",
    "env_worker_restarts",
    "env_degraded_to_sync",
    "rollout_bursts",
    "act_dispatches",
    "env_steps_jax",
    "plane_traj_slabs",
    "plane_player_restarts",
    "comms_ops",
    "comms_bytes",
    "comms_ms",
    "flight_dumps",
    "eval_rounds",
    "eval_episodes",
    "inrun_eval_publishes",
)


# -- live registry ------------------------------------------------------------


def publish_source(name: str, snapshot: Dict[str, Any]) -> None:
    """Publish (or refresh) one source's latest cumulative snapshot."""
    if not isinstance(snapshot, dict):
        return
    with _LOCK:
        _SOURCES[str(name)] = dict(snapshot)


def source_snapshots() -> Dict[str, Dict[str, Any]]:
    """The registry's current view, name-sorted (deterministic output)."""
    with _LOCK:
        return {name: dict(_SOURCES[name]) for name in sorted(_SOURCES)}


def clear_sources() -> None:
    with _LOCK:
        _SOURCES.clear()


# -- sidecars -----------------------------------------------------------------


def sidecar_path(tel_dir: str, name: str) -> str:
    return os.path.join(tel_dir, f"sidecar_{name}.json")


def write_sidecar(tel_dir: str, name: str, payload: Dict[str, Any]) -> Optional[str]:
    """Atomically write one source sidecar; best-effort (a full disk or a
    torn run dir must never take the producing process down)."""
    from sheeprl_tpu.obs.live import atomic_write_json

    path = sidecar_path(tel_dir, name)
    try:
        atomic_write_json(path, payload)
    except OSError:
        return None
    return path


def read_sidecars(tel_dir: str) -> Dict[str, Dict[str, Any]]:
    """All readable sidecars under a telemetry dir, name-keyed and sorted.

    A torn/unparseable file (the producer was SIGKILLed mid-write of a
    non-atomic copy, a foreign json landed in the dir) yields
    ``{"torn": True}`` so the merged view records that the source existed
    without poisoning the totals."""
    out: Dict[str, Dict[str, Any]] = {}
    for path in sorted(glob.glob(os.path.join(tel_dir, "sidecar_*.json"))):
        m = _SIDECAR_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
            if not isinstance(doc, dict):
                raise ValueError("sidecar is not an object")
        except Exception:
            out[m.group(1)] = {"torn": True}
            continue
        out[m.group(1)] = doc
    return out


# -- the merge ----------------------------------------------------------------


def _lift_env_pools(sources: Dict[str, Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Flatten env pools embedded in player sidecars to first-class
    ``player<k>/envpool*`` sources (players run their pools in-process; the
    learner only ever sees them through the player's sidecar)."""
    lifted: Dict[str, Dict[str, Any]] = {}
    for name, snap in sources.items():
        pools = snap.get("env_pools")
        if isinstance(pools, dict):
            for pool_name in sorted(pools):
                if isinstance(pools[pool_name], dict):
                    lifted[f"{name}/{pool_name}"] = dict(pools[pool_name])
    return lifted


def merge_into_summary(
    summary: Dict[str, Any],
    tel_dir: Optional[str],
    staleness_tracker: Any = None,
) -> Dict[str, Any]:
    """Fold every known source into the final run summary, in place.

    - ``sources``: sidecars (final truth) layered over the live registry,
      env pools lifted out of player sidecars, name-sorted.
    - rank sidecar counters are SUMMED into the summary's counter totals
      (they are folded nowhere else); player/env-pool counters are already
      folded live by the supervisor/pool and stay breakdown-only.
    - rank sidecar ``staleness_dump``s merge exactly into the run tracker
      (same log-bucket merge as the phase histograms) — the caller
      re-reads the tracker's summary afterwards.
    """
    sources: Dict[str, Dict[str, Any]] = dict(source_snapshots())
    if tel_dir:
        sources.update(read_sidecars(tel_dir))
    sources.update(_lift_env_pools(sources))
    if not sources:
        return summary

    for name in sorted(sources):
        snap = sources[name]
        if not name.startswith("rank") or snap.get("torn"):
            continue
        for field in SUMMED_RANK_COUNTERS:
            v = snap.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                base = summary.get(field)
                if isinstance(base, (int, float)) and not isinstance(base, bool):
                    summary[field] = type(base)(base + v) if isinstance(base, int) else base + v
        if staleness_tracker is not None and isinstance(snap.get("staleness_dump"), dict):
            try:
                staleness_tracker.merge_dict(snap["staleness_dump"])
            except Exception:
                pass  # a foreign/old-schema dump must not break finalize

    # breakdown entries: keep each source's own view, drop the bulky exact
    # histogram dumps (the merged percentiles already absorbed them)
    summary["sources"] = {
        name: {k: v for k, v in sources[name].items() if k != "staleness_dump"}
        for name in sorted(sources)
    }
    return summary
