"""Measured collective communication: spans, counters, achieved GB/s.

Two layers, matching where a collective can actually be timed:

- **Host-level collectives** (``Fabric.all_reduce`` / ``all_gather`` /
  ``broadcast`` / ``barrier`` — cross-process, dispatched from Python):
  :func:`collective_span` wraps each call in a ``Time/comms_<kind>_time``
  span (per-kind p50/p95/p99 via the streaming histograms), counts payload
  bytes and wall milliseconds into the run counters (``comms_ms`` /
  ``comms_bytes`` / ``comms_ops`` + a per-kind breakdown in
  ``telemetry.json``), and reports achieved GB/s against the device-link
  peak registry (:func:`sheeprl_tpu.obs.prof.roofline.detect_link_peaks`).
- **In-jit collectives** (the gradient ``pmean`` inside every train
  program): a host span cannot time an op fused into an XLA program, so
  :func:`pmean`/:func:`psum` are *chokepoints*, not timers — one named
  place every algo routes its gradient sync through (enforced by
  ``tools/lint_telemetry.py``), while the measured device time comes from
  the xplane comms attribution (``obs/prof/xplane.summarize_space`` →
  ``comms_ms_per_step`` in profiled captures).

Wire-byte accounting uses the standard ring factors so the reported GB/s is
what the link actually carried, not just the payload: all-reduce moves
``2(n-1)/n × payload`` per participant, all-gather/broadcast ``(n-1)/n`` of
the gathered/broadcast bytes, a barrier ~nothing.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Any, Dict, Optional

__all__ = [
    "all_gather",
    "collective_span",
    "pmean",
    "psum",
    "record_collective",
    "wire_bytes",
]

#: collective kinds the counters break down by
KINDS = ("all_reduce", "all_gather", "broadcast", "barrier")


def wire_bytes(kind: str, payload_bytes: int, n: int) -> int:
    """Bytes a ring implementation moves per participant for ``payload``.

    ``n`` is the number of participants; with ``n <= 1`` nothing crosses a
    link. The factors are the textbook ring costs (the same ones
    ``tools/bench_scaling.py`` projects with): 2(n-1)/n for all-reduce
    (reduce-scatter + all-gather phases), (n-1)/n for all-gather and for a
    pipelined broadcast, 0 for a barrier."""
    if n <= 1 or payload_bytes <= 0:
        return 0
    if kind == "all_reduce":
        return int(payload_bytes * 2 * (n - 1) / n)
    if kind in ("all_gather", "broadcast"):
        return int(payload_bytes * (n - 1) / n)
    return 0


def record_collective(
    kind: str, payload_bytes: int, seconds: float, world: int = 1
) -> Optional[float]:
    """Record one completed host-level collective into the run counters.

    Returns the achieved wire GB/s (None when nothing crossed a link or the
    clock did not advance). No-op when telemetry is off."""
    from sheeprl_tpu.obs import counters as _counters

    c = _counters.installed()
    if c is None:
        return None
    wire = wire_bytes(kind, payload_bytes, world)
    gbps = (wire / seconds / 1e9) if (wire and seconds > 0) else None
    c.add_comms(kind, payload_bytes, seconds * 1e3, gbps)
    return gbps


@contextmanager
def collective_span(kind: str, payload_bytes: int = 0, world: Optional[int] = None):
    """Span + counter accounting around one host-level collective.

    The span feeds the per-kind streaming histogram and the trace timeline
    (``Time/comms_<kind>_time``, phase ``comms``); the counter side records
    payload/wire bytes, wall ms, and achieved GB/s. ``world`` defaults to
    ``jax.process_count()`` — the participants of the fabric's host-level
    collectives."""
    from sheeprl_tpu.obs.spans import span

    if world is None:
        try:
            import jax

            world = int(jax.process_count())
        except Exception:
            world = 1
    t0 = time.perf_counter()
    with span(f"Time/comms_{kind}_time", phase="comms"):
        yield
    record_collective(kind, int(payload_bytes), time.perf_counter() - t0, world)


def link_peak_gbps() -> Optional[float]:
    """This host's device-link peak GB/s (ICI for TPUs, estimated loopback
    for CPU test meshes) from the roofline registry, or None."""
    from sheeprl_tpu.obs.prof.roofline import detect_link_peaks

    return detect_link_peaks().get("link_gbps")


# -- in-jit chokepoints -------------------------------------------------------
#
# These are the ONLY way algo code may spell a traced collective
# (tools/lint_telemetry.py rejects raw jax.lax.* collectives in algos/).
# They cannot be host-timed — the op lowers into the XLA program — but going
# through one named seam means (a) the xplane parser's collective-op
# attribution (obs/prof) is the agreed measurement, and (b) a future
# latency-hiding rewrite (e.g. overlapping the gradient sync with the
# backward pass) is one edit, not seventeen.


def pmean(x: Any, axis_name: Optional[str]) -> Any:
    """Mean-all-reduce over a mesh axis inside a jitted program (the
    gradient sync every train step runs). Device time is attributed by the
    profiled-capture comms split, not a host span.

    ``axis_name=None`` is the identity: sharded-parameter train steps run as
    one *global* GSPMD program (no manual axis — the batch mean already spans
    the whole mesh and XLA inserts the gradient reduce-scatter itself)."""
    import jax

    if axis_name is None:
        return x
    return jax.lax.pmean(x, axis_name)


def psum(x: Any, axis_name: Optional[str]) -> Any:
    """Sum-all-reduce over a mesh axis inside a jitted program.
    ``axis_name=None`` is the identity (see :func:`pmean`)."""
    import jax

    if axis_name is None:
        return x
    return jax.lax.psum(x, axis_name)


def all_gather(x: Any, axis_name: str, **kwargs: Any) -> Any:
    """All-gather over a mesh axis inside a jitted program (DV3's Moments
    percentile gather)."""
    import jax

    return jax.lax.all_gather(x, axis_name, **kwargs)
