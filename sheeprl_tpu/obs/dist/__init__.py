"""Distributed observability plane: measured collectives, cross-process
telemetry aggregation, and data-staleness lineage.

The repo runs as a multi-process system — ``jax.distributed`` ranks
(``fabric.py``), actor–learner plane players (``sheeprl_tpu/plane``), async
env workers (``envs/vector``) — but the PR-1/4/8 observability layers were
learner-process-centric. This package is the systemwide half
(``howto/distributed_obs.md``):

- :mod:`~sheeprl_tpu.obs.dist.comms` — host-level collective spans
  (payload bytes, wall time, achieved GB/s vs the device-link peak registry)
  wrapped around the fabric collectives, plus the in-jit ``pmean``/``psum``
  chokepoints every algo train step routes its gradient sync through
  (enforced by ``tools/lint_telemetry.py``; device time attributed by the
  xplane comms parser in ``obs/prof``);
- :mod:`~sheeprl_tpu.obs.dist.aggregate` — the rank-0/learner-side merge of
  counters, histograms, and live snapshots from every source process
  (ranks, plane players, env-worker pools) into ONE ``telemetry.json`` /
  ``live.json`` view with a per-source breakdown;
- :mod:`~sheeprl_tpu.obs.dist.staleness` — trajectory lineage: rows are
  stamped at env-step/slab-commit time and training batches carry
  ``sample_age_s`` and ``policy_lag_versions`` percentiles plus
  slab/prefetch queue-depth gauges.

Like the rest of ``obs``, everything here is a no-op until
``setup_telemetry`` installs it.
"""

from sheeprl_tpu.obs.dist.aggregate import (
    merge_into_summary,
    publish_source,
    read_sidecars,
    source_snapshots,
    write_sidecar,
)
from sheeprl_tpu.obs.dist.comms import (
    all_gather as instrumented_all_gather,
    collective_span,
    pmean,
    psum,
    wire_bytes,
)
from sheeprl_tpu.obs.dist.staleness import StalenessTracker

__all__ = [
    "StalenessTracker",
    "collective_span",
    "instrumented_all_gather",
    "merge_into_summary",
    "pmean",
    "psum",
    "publish_source",
    "read_sidecars",
    "source_snapshots",
    "wire_bytes",
    "write_sidecar",
]
