"""Parse ``jax.profiler`` xplane traces into per-module device time.

Promoted from ``tools/parse_xplane.py`` (which stays as a thin CLI shim).
Two deliberate departures from the tool it replaces:

- **No tensorflow import.** The original leaned on the proto bundled in
  tensorflow (``tensorflow.tsl.profiler.protobuf.xplane_pb2``) plus a
  ``PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python`` env dance. The XSpace
  schema is tiny and stable, so this module decodes the protobuf wire
  format directly — the parser now works in-run, in tests, and in images
  without tensorflow.
- **CPU host-plane fallback.** TPU/GPU traces carry a device plane with the
  authoritative ``XLA Modules`` line (one event per executed module). CPU
  traces have no device plane at all; there the host plane's
  ``PjitFunction(<name>)`` events are the per-dispatch record (verified on
  the pinned jax 0.4.37: each dispatch emits a nested pair of identical
  spans, which the outermost-merge below collapses to one execution). CPU
  numbers are host-thread time, not accelerator time — good enough for the
  e2e plumbing and for relative per-family comparisons on one host.

Per-step attribution is **occurrence-based**: one ``XLA Modules`` /
``PjitFunction`` event per program execution, so ``ms_per_exec`` needs no
host-side step counting (the caller maps executions to train-step units).
"""

from __future__ import annotations

import collections
import glob
import os
import re
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = [
    "find_xplane",
    "load_xspace",
    "phase_of",
    "summarize",
    "summarize_space",
]


# -- protobuf wire decoding ---------------------------------------------------
# Schema (tensorflow/tsl/profiler/protobuf/xplane.proto), fields we read:
#   XSpace:         planes = 1 (repeated XPlane)
#   XPlane:         name = 2, lines = 3 (repeated XLine),
#                   event_metadata = 4 (map<int64, XEventMetadata>)
#   XLine:          name = 2, events = 4 (repeated XEvent)
#   XEvent:         metadata_id = 1, offset_ps = 2, duration_ps = 3
#   XEventMetadata: id = 1, name = 2
#   map entry:      key = 1, value = 2


def _fields(buf: bytes) -> Iterator[Tuple[int, int, Any]]:
    """Yield (field_number, wire_type, value) for one message's wire bytes.

    Varints decode as ints; length-delimited as bytes; fixed64/fixed32 as
    ints. Unknown/grouped wire types abort the remainder of the message
    (tolerant-by-truncation: a malformed tail loses events, not the parse).
    """
    i, n = 0, len(buf)
    while i < n:
        tag, i = _varint(buf, i)
        if tag is None:
            return
        field, wire = tag >> 3, tag & 7
        if wire == 0:  # varint
            val, i = _varint(buf, i)
            if val is None:
                return
        elif wire == 1:  # fixed64
            if i + 8 > n:
                return
            val, i = int.from_bytes(buf[i : i + 8], "little"), i + 8
        elif wire == 2:  # length-delimited
            ln, i = _varint(buf, i)
            if ln is None or i + ln > n:
                return
            val, i = buf[i : i + ln], i + ln
        elif wire == 5:  # fixed32
            if i + 4 > n:
                return
            val, i = int.from_bytes(buf[i : i + 4], "little"), i + 4
        else:  # groups (3/4) never appear in xplane protos
            return
        yield field, wire, val


def _varint(buf: bytes, i: int) -> Tuple[Optional[int], int]:
    result = shift = 0
    n = len(buf)
    while i < n:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7
        if shift > 70:
            break
    return None, i


def load_xspace(path: str) -> List[Dict[str, Any]]:
    """Decode one ``*.xplane.pb`` into a list of plane dicts:
    ``{"name", "lines": [{"name", "events": [(meta_id, offset_ps, dur_ps)]}],
    "event_names": {meta_id: name}}``."""
    with open(path, "rb") as f:
        data = f.read()
    planes = []
    for field, wire, val in _fields(data):
        if field == 1 and wire == 2:
            planes.append(_decode_plane(val))
    return planes


def _decode_plane(buf: bytes) -> Dict[str, Any]:
    plane: Dict[str, Any] = {"name": "", "lines": [], "event_names": {}}
    for field, wire, val in _fields(buf):
        if field == 2 and wire == 2:
            plane["name"] = val.decode("utf-8", "replace")
        elif field == 3 and wire == 2:
            plane["lines"].append(_decode_line(val))
        elif field == 4 and wire == 2:  # event_metadata map entry
            key, meta_name = None, ""
            for f2, w2, v2 in _fields(val):
                if f2 == 1 and w2 == 0:
                    key = v2
                elif f2 == 2 and w2 == 2:  # XEventMetadata
                    for f3, w3, v3 in _fields(v2):
                        if f3 == 1 and w3 == 0 and key is None:
                            key = v3
                        elif f3 == 2 and w3 == 2:
                            meta_name = v3.decode("utf-8", "replace")
            if key is not None:
                plane["event_names"][key] = meta_name
    return plane


def _decode_line(buf: bytes) -> Dict[str, Any]:
    line: Dict[str, Any] = {"name": "", "events": []}
    for field, wire, val in _fields(buf):
        if field == 2 and wire == 2:
            line["name"] = val.decode("utf-8", "replace")
        elif field == 4 and wire == 2:
            meta_id = offset_ps = dur_ps = 0
            for f2, w2, v2 in _fields(val):
                if w2 != 0:
                    continue
                if f2 == 1:
                    meta_id = v2
                elif f2 == 2:
                    offset_ps = v2
                elif f2 == 3:
                    dur_ps = v2
            line["events"].append((meta_id, offset_ps, dur_ps))
    return line


# -- trace location -----------------------------------------------------------


def find_xplane(trace_dir: str) -> str:
    """Newest ``*.xplane.pb`` under ``trace_dir`` (the layout
    ``jax.profiler`` writes: ``<dir>/plugins/profile/<ts>/<host>.xplane.pb``),
    or the path itself when it already names a file."""
    if os.path.isfile(trace_dir):
        return trace_dir
    files = sorted(
        glob.glob(os.path.join(trace_dir, "plugins", "profile", "*", "*.xplane.pb"))
    ) or sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True))
    if not files:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    return files[-1]


# -- phase attribution --------------------------------------------------------

#: framework-phase heuristics over XLA module / jit-function names. Every
#: family jits its fused train program as ``shmapped`` (the shard_map
#: wrapper in build_train_fn), DV3's burst path as ``burst``; the rollout
#: engine's jitted collector and the device-ring gather have their own
#: names. First match wins; unmatched modules report phase ``other``.
_PHASE_PATTERNS: Tuple[Tuple[str, "re.Pattern"], ...] = (
    ("train", re.compile(r"shmapped|burst|train|update|local_step", re.I)),
    ("rollout", re.compile(r"rollout|collect|scan_rollout", re.I)),
    ("act", re.compile(r"\bact|player|policy|greedy|sample_act", re.I)),
    ("staging", re.compile(r"gather|stage|prefetch|ring|sample", re.I)),
    ("publish", re.compile(r"publish|broadcast", re.I)),
)


def phase_of(module_name: str) -> str:
    """Map an XLA module / jit-function name onto a framework phase name."""
    for phase, pattern in _PHASE_PATTERNS:
        if pattern.search(module_name):
            return phase
    return "other"


def _clean_module_name(name: str) -> str:
    """``jit_shmapped.2`` / ``PjitFunction(shmapped)`` -> ``shmapped``."""
    m = re.match(r"PjitFunction\((.*)\)$", name)
    if m:
        name = m.group(1)
    name = re.sub(r"^jit_", "", name)
    return re.sub(r"\(\d+\)$|\.\d+$", "", name) or name


# -- summarize ----------------------------------------------------------------


def _merge_outermost(
    intervals: List[Tuple[int, int]]
) -> Tuple[int, int]:
    """(execs, total_ps) counting only outermost spans — host traces emit
    nested duplicate spans per dispatch (PjitFunction inside PjitFunction)."""
    execs = total = 0
    current_end = -1
    for start, end in sorted(intervals):
        if start >= current_end:
            execs += 1
            total += end - start
            current_end = end
        elif end > current_end:  # partial overlap: extend, same execution
            total += end - current_end
            current_end = end
    return execs, total


def _module_records(
    events: List[Tuple[str, int, int]]
) -> Dict[str, Dict[str, Any]]:
    """name -> {execs, total_ms, ms_per_exec, phase} from (name, start, dur)."""
    by_name: Dict[str, List[Tuple[int, int]]] = collections.defaultdict(list)
    for name, start, dur in events:
        by_name[name].append((start, start + dur))
    out: Dict[str, Dict[str, Any]] = {}
    for name, intervals in by_name.items():
        execs, total_ps = _merge_outermost(intervals)
        if execs == 0:
            continue
        out[name] = {
            "execs": execs,
            "total_ms": total_ps / 1e9,
            "ms_per_exec": total_ps / 1e9 / execs,
            "phase": phase_of(name),
        }
    return out


def _op_self_times(plane: Dict[str, Any]) -> "collections.Counter":
    """Per-op self-time (ps) via a stack sweep over the nested 'XLA Ops'
    events. 'Async XLA Ops' durations overlap and must not be summed —
    that line is deliberately ignored."""
    ops_line = next((l for l in plane["lines"] if l["name"] == "XLA Ops"), None)
    if ops_line is None:
        return collections.Counter()
    names = plane["event_names"]
    evs = sorted(
        (off, off + dur, names.get(mid, f"op_{mid}"))
        for mid, off, dur in ops_line["events"]
    )
    self_time: collections.Counter = collections.Counter()
    stack: List[Tuple[int, int, str]] = []
    for start, end, name in evs:
        while stack and stack[-1][1] <= start:
            stack.pop()
        if stack:
            self_time[stack[-1][2]] -= min(end, stack[-1][1]) - start
        self_time[name] += end - start
        stack.append((start, end, name))
    return self_time


def _top_ops(plane: Dict[str, Any], limit: int = 30) -> Dict[str, float]:
    """Self-time (ms, summed over the capture) of the hottest XLA ops."""
    return {name: ps / 1e9 for name, ps in _op_self_times(plane).most_common(limit)}


#: HLO op-name categories that are collective communication — the device
#: time XLA spends moving gradients/activations between chips rather than
#: computing (sync-variant names like `all-reduce-start`/`-done` and fused
#: spellings like `all-reduce.1` / `fusion.all-reduce` all match). Order
#: matters for classification: `reduce-scatter` must win over a bare
#: `all-reduce` substring match, so kinds are probed in listed order.
_COLLECTIVE_KINDS = (
    "reduce-scatter",
    "all-reduce",
    "all-gather",
    "collective-permute",
    "all-to-all",
)
_COLLECTIVE_OP = re.compile("|".join(_COLLECTIVE_KINDS), re.I)


def _collective_kind(name: str) -> str | None:
    """Collective category of an HLO op name, or None for compute ops."""
    low = name.lower()
    for kind in _COLLECTIVE_KINDS:
        if kind in low:
            return kind
    return None


def _collective_ms(self_times: "collections.Counter") -> float:
    """Total collective-op self-time (ms) over one capture — the comms half
    of the compute-vs-comms split in profiled `device_ms_per_step`."""
    return sum(
        ps for name, ps in self_times.items() if _COLLECTIVE_OP.search(name)
    ) / 1e9


def _collective_ms_by_kind(self_times: "collections.Counter") -> Dict[str, float]:
    """Collective self-time (ms) split by category. Gradient all-reduce vs
    parameter all-gather vs reduce-scatter bind differently under parameter
    sharding (howto/sharding.md), so the roofline report keeps them apart."""
    by_kind: Dict[str, float] = {}
    for name, ps in self_times.items():
        kind = _collective_kind(name)
        if kind is not None:
            by_kind[kind] = by_kind.get(kind, 0.0) + ps / 1e9
    return by_kind


def summarize_space(planes: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Attribute one decoded trace: per-module executions and device time.

    Returns::

        {
          "source":       "device" | "host",   # which plane was attributable
          "plane":        plane name,
          "modules":      {name: {execs, total_ms, ms_per_exec, phase}},
          "train_module": name | None,         # dominant phase=='train' module
          "window_ms":    capture span on that plane,
          "busy_ms":      sum of module time,
          "busy_frac":    busy_ms / window_ms (device idleness == dispatch gaps),
          "steps_ms_total": 'Steps' line total (device planes, else None),
          "top_ops":      {op: self_ms_total} (device planes, else {}),
        }
    """
    device_plane = next(
        (p for p in planes if "TPU" in p["name"] or "GPU" in p["name"]), None
    )
    if device_plane is not None:
        events = []
        for line in device_plane["lines"]:
            if line["name"] == "XLA Modules":
                names = device_plane["event_names"]
                events = [
                    (_clean_module_name(names.get(mid, f"module_{mid}")), off, dur)
                    for mid, off, dur in line["events"]
                ]
        modules = _module_records(events)
        steps_line = next(
            (l for l in device_plane["lines"] if l["name"] == "Steps"), None
        )
        out = _assemble(device_plane, "device", modules, events)
        out["steps_ms_total"] = (
            sum(d for _m, _o, d in steps_line["events"]) / 1e9
            if steps_line is not None
            else None
        )
        self_times = _op_self_times(device_plane)
        out["top_ops"] = {
            name: ps / 1e9 for name, ps in self_times.most_common(30)
        }
        # collective-op device time: present (possibly 0.0) whenever the
        # trace carries an op line, None when ops were not recorded at all
        out["comms_ms_total"] = round(_collective_ms(self_times), 4) if self_times else None
        out["comms_ms_by_kind"] = (
            {k: round(v, 4) for k, v in sorted(_collective_ms_by_kind(self_times).items())}
            if self_times
            else None
        )
        return out

    # CPU fallback: PjitFunction(...) dispatch spans on the host plane
    host_plane = next(
        (p for p in planes if "host" in p["name"].lower() and p["lines"]), None
    )
    if host_plane is None:
        raise FileNotFoundError(
            f"no attributable plane in trace (planes: {[p['name'] for p in planes]})"
        )
    names = host_plane["event_names"]
    events = []
    for line in host_plane["lines"]:
        for mid, off, dur in line["events"]:
            name = names.get(mid, "")
            if name.startswith("PjitFunction("):
                events.append((_clean_module_name(name), off, dur))
    modules = _module_records(events)
    out = _assemble(host_plane, "host", modules, events)
    out["steps_ms_total"] = None
    out["top_ops"] = {}
    out["comms_ms_total"] = None  # host dispatch spans carry no op split
    out["comms_ms_by_kind"] = None
    return out


def _assemble(
    plane: Dict[str, Any],
    source: str,
    modules: Dict[str, Dict[str, Any]],
    events: List[Tuple[str, int, int]],
) -> Dict[str, Any]:
    # window = first module start -> last module end, NOT the whole trace:
    # host planes carry profiler-setup spans that would otherwise dilute
    # busy_frac into a spurious dispatch-bound verdict
    starts = [off for _n, off, _d in events]
    ends = [off + dur for _n, off, dur in events]
    window_ms = (max(ends) - min(starts)) / 1e9 if starts else 0.0
    busy_ms = sum(m["total_ms"] for m in modules.values())
    train_candidates = {
        n: m for n, m in modules.items() if m["phase"] == "train"
    } or modules
    train_module = (
        max(train_candidates, key=lambda n: train_candidates[n]["total_ms"])
        if train_candidates
        else None
    )
    return {
        "source": source,
        "plane": plane["name"],
        "modules": modules,
        "train_module": train_module,
        "window_ms": round(window_ms, 3),
        "busy_ms": round(busy_ms, 3),
        "busy_frac": round(busy_ms / window_ms, 4) if window_ms > 0 else None,
    }


def summarize(trace_dir: str, n_steps: Optional[int] = None) -> Dict[str, Any]:
    """Parse the newest xplane under ``trace_dir``.

    The full occurrence-based attribution (:func:`summarize_space`) plus,
    when ``n_steps`` is given, the legacy divide-by-n keys the original
    ``tools/parse_xplane.py`` exposed (``modules_us_per_step`` /
    ``steps_us_per_step`` / ``top_ops`` in us/step) so existing consumers
    (``bench_dreamer.py``) keep working.
    """
    out = summarize_space(load_xspace(find_xplane(trace_dir)))
    if n_steps is not None:
        denom = max(n_steps, 1)
        busy_us = out["busy_ms"] * 1e3
        out["modules_us_per_step"] = busy_us / denom if out["modules"] else None
        out["steps_us_per_step"] = (
            out["steps_ms_total"] * 1e3 / denom
            if out.get("steps_ms_total") is not None
            else None
        )
        out["top_ops"] = {k: v * 1e3 / denom for k, v in out["top_ops"].items()}
    return out
