"""Roofline accounting: FLOPs + bytes-accessed vs measured device time.

PaLM-style MFU (Chowdhery et al., 2022) extended with the bandwidth side of
the roofline: for a profiled program we know its analytic cost
(``Compiled.cost_analysis()`` FLOPs and bytes accessed) and its measured
per-execution device time (``obs/prof/xplane.py``), so we can say — per XLA
module, per family — whether the hardware was bound by **compute** (MFU is
the ceiling), **HBM bandwidth** (achieved GB/s is the ceiling), or by
**dispatch gaps** (the device sat idle waiting on the host, and no kernel
work will help until the dispatch path does). ROADMAP item 4 needs exactly
this verdict per Dreamer family before choosing a Pallas target.

Peak numbers come from a small device registry keyed on
``jax.devices()[0].device_kind`` with a CPU fallback estimated from the core
count — estimated peaks are flagged ``estimated: True`` and make the
*relative* verdicts meaningful on hosts without an accelerator (tests, the
CI dry-run), while absolute MFU on CPU is read as indicative only.
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, Optional

__all__ = [
    "DEVICE_PEAKS",
    "LINK_PEAKS",
    "cost_bytes",
    "cost_of",
    "detect_link_peaks",
    "detect_peaks",
    "roofline_analyze",
]

#: device_kind pattern -> (peak TFLOP/s in bf16, peak HBM GB/s). Single-chip
#: numbers from the vendor datasheets; the MFU denominator stays the chip's
#: bf16 peak for 32-true programs too (same convention as obs/perf.py).
DEVICE_PEAKS = (
    (r"TPU v6|Trillium", {"label": "TPU v6e", "peak_tflops": 918.0, "peak_gbps": 1640.0}),
    (r"TPU v5p", {"label": "TPU v5p", "peak_tflops": 459.0, "peak_gbps": 2765.0}),
    (r"TPU v5|v5 ?lite", {"label": "TPU v5e", "peak_tflops": 197.0, "peak_gbps": 819.0}),
    (r"TPU v4", {"label": "TPU v4", "peak_tflops": 275.0, "peak_gbps": 1228.0}),
    (r"TPU v3", {"label": "TPU v3", "peak_tflops": 123.0, "peak_gbps": 900.0}),
    (r"TPU v2", {"label": "TPU v2", "peak_tflops": 46.0, "peak_gbps": 700.0}),
    (r"H100", {"label": "H100", "peak_tflops": 989.0, "peak_gbps": 3350.0}),
    (r"A100", {"label": "A100", "peak_tflops": 312.0, "peak_gbps": 2039.0}),
    (r"V100", {"label": "V100", "peak_tflops": 125.0, "peak_gbps": 900.0}),
    (r"RTX 3080|GeForce RTX 3080", {"label": "RTX 3080", "peak_tflops": 59.5, "peak_gbps": 760.0}),
)


#: device_kind pattern -> inter-chip link peak, GB/s per link per direction
#: (ICI for TPUs from the public specs — the same ballpark
#: tools/bench_scaling.py projects with; NVLink-generation numbers for the
#: GPUs). The comms instrumentation (obs/dist/comms.py) reports achieved
#: wire GB/s against this as `link_util_pct`.
LINK_PEAKS = (
    (r"TPU v6|Trillium", 90.0),
    (r"TPU v5p", 100.0),
    (r"TPU v5|v5 ?lite", 45.0),
    (r"TPU v4", 50.0),
    (r"TPU v3", 70.0),
    (r"TPU v2", 62.5),
    (r"H100", 450.0),
    (r"A100", 300.0),
    (r"V100", 150.0),
)


def detect_link_peaks(link_gbps: Optional[float] = None) -> Dict[str, Any]:
    """Inter-chip link peak for this host's first jax device.

    Returns ``{label, device_kind, link_gbps, estimated}``. On CPU test
    meshes the "link" is the host's own memory system (gloo over loopback
    for multi-process runs) — estimated from the DDR figure so the relative
    utilization numbers stay meaningful; an explicit ``link_gbps`` override
    always wins."""
    kind = "unknown"
    try:
        import jax

        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", dev.platform)
    except Exception:
        pass
    out: Dict[str, Any] = {"device_kind": kind, "estimated": False}
    for pattern, gbps in LINK_PEAKS:
        if re.search(pattern, kind, re.I):
            out.update({"label": kind, "link_gbps": gbps})
            break
    else:
        # CPU / unknown device: loopback collectives bottleneck on memcpy
        # bandwidth — reuse the estimated DDR figure, flagged estimated
        out.update({"label": f"{kind} (estimated link)", "link_gbps": _cpu_peaks()["peak_gbps"], "estimated": True})
    if link_gbps:
        out["link_gbps"] = float(link_gbps)
        out["estimated"] = False
    return out


def _cpu_peaks() -> Dict[str, Any]:
    """Order-of-magnitude CPU peaks so the roofline runs everywhere: AVX-512
    FMA at 32 FLOPs/cycle/core × a nominal 3 GHz, and a nominal dual-channel
    DDR bandwidth. Flagged estimated — the verdicts stay comparative."""
    cores = os.cpu_count() or 1
    return {
        "label": f"CPU ({cores} cores, estimated)",
        "peak_tflops": round(cores * 3.0e9 * 32 / 1e12, 2),
        "peak_gbps": 64.0,
        "estimated": True,
    }


def detect_peaks(
    peak_tflops: Optional[float] = None, peak_gbps: Optional[float] = None
) -> Dict[str, Any]:
    """Peak numbers for this host's first jax device (overridable).

    Returns ``{label, platform, device_kind, peak_tflops, peak_gbps,
    estimated}``; explicit overrides win over the registry."""
    platform = kind = "unknown"
    try:
        import jax

        dev = jax.devices()[0]
        platform, kind = dev.platform, getattr(dev, "device_kind", dev.platform)
    except Exception:
        pass
    peaks: Dict[str, Any] = {"estimated": False}
    if platform == "cpu" or kind == "unknown":
        peaks.update(_cpu_peaks())
    else:
        for pattern, entry in DEVICE_PEAKS:
            if re.search(pattern, kind, re.I):
                peaks.update(entry)
                break
        else:
            peaks.update({"label": kind, "peak_tflops": None, "peak_gbps": None})
    peaks["platform"] = platform
    peaks["device_kind"] = kind
    if peak_tflops:
        peaks["peak_tflops"] = float(peak_tflops)
    if peak_gbps:
        peaks["peak_gbps"] = float(peak_gbps)
    if peak_tflops and peak_gbps:
        # only a FULL override clears the flag — with one axis still guessed
        # the verdict is still derived from an estimated peak
        peaks["estimated"] = False
    return peaks


# -- cost analysis ------------------------------------------------------------


def _analysis_dict(compiled) -> Dict[str, Any]:
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    return ca


def cost_bytes(compiled) -> float:
    """Bytes accessed by a compiled XLA module per ``cost_analysis()`` (the
    HBM traffic bound; same while-loop body-once caveat as ``cost_flops``)."""
    return float(_analysis_dict(compiled).get("bytes accessed", 0.0))


def cost_of(jit_fn, *args, **kwargs) -> Optional[Dict[str, float]]:
    """``{"flops", "bytes_accessed"}`` of ``jit_fn(*args)`` via AOT
    lower+compile, or None when the backend has no cost model (tests assert
    the None path — a missing cost analysis must never break a run).

    Pass :func:`~sheeprl_tpu.obs.perf.shape_specs` of the arguments rather
    than live arrays when the call donates buffers."""
    try:
        ca = _analysis_dict(jit_fn.lower(*args, **kwargs).compile())
        return {
            "flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        }
    except Exception:
        return None


# -- the verdict --------------------------------------------------------------


def roofline_analyze(
    flops_per_exec: Optional[float],
    bytes_per_exec: Optional[float],
    device_ms_per_exec: Optional[float],
    busy_frac: Optional[float] = None,
    peaks: Optional[Dict[str, Any]] = None,
    dispatch_busy_threshold: float = 0.5,
) -> Dict[str, Any]:
    """Classify one program's binding constraint from its measured roofline.

    Rules, in order:

    - no measured device time -> ``unmeasured`` (nothing else is computable);
    - the device was busy less than ``dispatch_busy_threshold`` of the
      profiled window -> ``dispatch-bound`` (the step path waits on the
      host; per-module utilization is still reported but is not the
      constraint);
    - otherwise, whichever of compute utilization (MFU) and bandwidth
      utilization is higher is the wall being pushed: ``compute-bound`` or
      ``memory-bound``. With no cost analysis available the verdict degrades
      to ``unknown``.

    Returns ``{mfu_pct, achieved_gbps, bandwidth_util_pct,
    arithmetic_intensity, ridge_intensity, verdict, peaks}``.
    """
    peaks = peaks or detect_peaks()
    out: Dict[str, Any] = {
        "mfu_pct": None,
        "achieved_gbps": None,
        "bandwidth_util_pct": None,
        "arithmetic_intensity": None,
        "ridge_intensity": None,
        "verdict": "unmeasured",
        "peaks": peaks,
    }
    peak_tflops, peak_gbps = peaks.get("peak_tflops"), peaks.get("peak_gbps")
    if peak_tflops and peak_gbps:
        out["ridge_intensity"] = round(peak_tflops * 1e12 / (peak_gbps * 1e9), 1)
    if not device_ms_per_exec or device_ms_per_exec <= 0:
        return out
    seconds = device_ms_per_exec / 1e3
    if flops_per_exec and bytes_per_exec:
        out["arithmetic_intensity"] = round(flops_per_exec / bytes_per_exec, 2)
    if flops_per_exec and peak_tflops:
        out["mfu_pct"] = round(
            flops_per_exec / seconds / (peak_tflops * 1e12) * 100.0, 3
        )
    if bytes_per_exec:
        out["achieved_gbps"] = round(bytes_per_exec / seconds / 1e9, 2)
        if peak_gbps:
            out["bandwidth_util_pct"] = round(
                out["achieved_gbps"] / peak_gbps * 100.0, 3
            )
    if busy_frac is not None and busy_frac < dispatch_busy_threshold:
        out["verdict"] = "dispatch-bound"
    elif out["mfu_pct"] is None and out["bandwidth_util_pct"] is None:
        out["verdict"] = "unknown"
    elif (out["mfu_pct"] or 0.0) >= (out["bandwidth_util_pct"] or 0.0):
        out["verdict"] = "compute-bound"
    else:
        out["verdict"] = "memory-bound"
    return out
