"""One builder for "a family's real train step on synthetic data".

The same setup — compose a config, stand up a 1-device Fabric, build the
agent through the family's *real* ``build_agent`` / ``build_train_fn``
wiring, synthesize a correctly-shaped batch, warm up — existed in four
places before this module (``bench_dreamer.py``, ``tools/profile_step.py``,
and the ``tools/diag_dv3_*`` one-offs), each hard-wired to one family.
:func:`build_harness` is the one implementation: every family the roofline
report profiles (all Dreamer generations, their P2E exploration variants,
SAC, PPO) builds through it, so a profiled number always measures the same
program the training loop dispatches.

The returned :class:`Harness` runs dispatches (threading the donated state
functionally), exposes the jitted program + pre-captured abstract arg specs
for ``cost_analysis`` (donation-safe), and hands back the raw pieces
(``world_model``/``actor``/…) for diagnostic tools that probe beyond
stepping.
"""

from __future__ import annotations

import importlib
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

__all__ = ["FAMILIES", "Harness", "build_harness", "tiny_overrides"]

#: family -> (algo module, default exp override, train program takes tau)
FAMILIES: Dict[str, Tuple[str, str, bool]] = {
    "dv1": ("dreamer_v1", "dreamer_v1", False),
    "dv2": ("dreamer_v2", "dreamer_v2_ms_pacman", True),
    "dv3": ("dreamer_v3", "dreamer_v3_100k_ms_pacman", True),
    "p2e_dv1": ("p2e_dv1", "p2e_dv1_exploration", False),
    "p2e_dv2": ("p2e_dv2", "p2e_dv2_exploration", True),
    "p2e_dv3": ("p2e_dv3", "p2e_dv3_exploration", True),
    "sac": ("sac", "sac", False),
    "ppo": ("ppo", "ppo", False),
}

#: the tiny preset keeps a full-wiring train step CPU-feasible (the same
#: shrink the policy-improvement tests use); SAC/PPO are already small
_DREAMER_TINY = (
    "per_rank_batch_size=4",
    "per_rank_sequence_length=8",
    "algo.horizon=5",
    "algo.dense_units=32",
    "algo.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=4",
    "algo.world_model.recurrent_model.recurrent_state_size=32",
    "algo.world_model.transition_model.hidden_size=32",
    "algo.world_model.representation_model.hidden_size=32",
    "cnn_keys.encoder=[rgb]",
)
_FAMILY_TINY = {
    "dv1": ("algo.world_model.stochastic_size=8",),
    "dv2": ("algo.world_model.stochastic_size=8", "algo.world_model.discrete_size=8"),
    "dv3": ("algo.world_model.stochastic_size=8", "algo.world_model.discrete_size=8"),
    "p2e_dv1": ("algo.world_model.stochastic_size=8",),
    "p2e_dv2": ("algo.world_model.stochastic_size=8", "algo.world_model.discrete_size=8"),
    "p2e_dv3": ("algo.world_model.stochastic_size=8", "algo.world_model.discrete_size=8"),
    "sac": (),
    "ppo": (),
}


def tiny_overrides(family: str) -> Tuple[str, ...]:
    """Config overrides shrinking ``family``'s model to CPU scale."""
    if family in ("sac", "ppo"):
        return _FAMILY_TINY[family]
    return _DREAMER_TINY + _FAMILY_TINY[family]


class Harness:
    """A runnable train step: ``run(n)`` dispatches n programs and blocks.

    ``jit_fn``/``arg_specs`` feed ``cost_of`` (specs are captured before the
    first call — the programs donate their state buffers). ``pieces`` holds
    the family's raw build products for diagnostic probing.
    """

    def __init__(
        self,
        family: str,
        cfg,
        fabric,
        jit_fn,
        arg_specs: Tuple[Any, ...],
        step_fn: Callable[[int], Any],
        block_fn: Callable[[Any], None],
        pieces: Dict[str, Any],
    ):
        self.family = family
        self.cfg = cfg
        self.fabric = fabric
        self.jit_fn = jit_fn
        self.arg_specs = arg_specs
        self._step_fn = step_fn
        self._block_fn = block_fn
        self.pieces = pieces
        self.steps_per_dispatch = 1
        self.dispatches = 0

    def run(self, n: int = 1) -> None:
        """Dispatch ``n`` train programs and block on the last result."""
        out = None
        for _ in range(int(n)):
            out = self._step_fn(self.dispatches)
            self.dispatches += 1
        if out is not None:
            self._block_fn(out)

    @property
    def state(self):
        """The live (donated-and-rethreaded) train state, where exposed."""
        box = self.pieces.get("state_box")
        return box["state"] if box else None

    def cost(self) -> Optional[Dict[str, float]]:
        """``{"flops", "bytes_accessed"}`` of one dispatch, or None."""
        from sheeprl_tpu.obs.prof.roofline import cost_of

        return cost_of(self.jit_fn, *self.arg_specs)


def build_harness(
    family: str,
    overrides: Sequence[str] = (),
    tiny: bool = False,
    seed: int = 0,
    actions: Optional[int] = None,
    exp: Optional[str] = None,
) -> Harness:
    """Build ``family``'s train step on synthetic data (compiled, unwarmed —
    the first ``run`` pays the compile). ``actions`` overrides the dreamer
    families' synthetic discrete action count (default 9, MsPacman's);
    ``exp`` swaps the composed experiment preset (diagnostic tools pin the
    bare family exp instead of the benched 100k preset)."""
    if family not in FAMILIES:
        raise ValueError(f"unknown family {family!r}; choose from {sorted(FAMILIES)}")
    import jax

    # eager init work stays on the host (bench_dreamer's rationale: on a
    # remote-attached device every eager op is a dispatch round trip)
    jax.config.update("jax_default_device", jax.devices("cpu")[0])
    ovr = list(tiny_overrides(family) if tiny else ()) + list(overrides)
    if family in ("sac", "ppo"):
        return _build_flat(family, ovr, seed)
    return _build_dreamer(family, ovr, seed, actions, exp)


def _compose(exp: str, overrides: Sequence[str]):
    from sheeprl_tpu.config.engine import compose

    return compose(
        "config",
        overrides=[
            f"exp={exp}",
            "env=dummy",
            "env.id=discrete_dummy",
            "metric.log_level=0",
            "buffer.checkpoint=False",
            "checkpoint.every=1000000",
            *overrides,
        ],
    )


def _fabric(cfg):
    from sheeprl_tpu.fabric import Fabric

    return Fabric(
        devices=cfg.fabric.get("devices", 1),
        accelerator=cfg.fabric.get("accelerator", "auto"),
        precision=cfg.fabric.get("precision", "32-true"),
    )


# -- dreamer generations + their P2E exploration variants ---------------------


def _build_dreamer(
    family: str,
    overrides: Sequence[str],
    seed: int,
    actions: Optional[int] = None,
    exp: Optional[str] = None,
) -> Harness:
    import gymnasium as gym
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.obs.perf import shape_specs

    module_name, default_exp, has_tau = FAMILIES[family]
    cfg = _compose(exp or default_exp, overrides)
    fabric = _fabric(cfg)
    agent_mod = importlib.import_module(f"sheeprl_tpu.algos.{module_name}.agent")
    algo_name = module_name + ("_exploration" if family.startswith("p2e") else "")
    algo_mod = importlib.import_module(f"sheeprl_tpu.algos.{module_name}.{algo_name}")

    obs_space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (3, 64, 64), np.uint8)})
    actions_dim = (int(actions or dict(cfg.get("bench", {})).get("actions", 9)),)
    key = jax.random.PRNGKey(seed)

    pieces: Dict[str, Any] = {"cfg": cfg, "fabric": fabric}
    if family.startswith("p2e"):
        from sheeprl_tpu.config.instantiate import instantiate

        world_model, actor, critic, ensemble_member, params = agent_mod.build_agent(
            cfg, actions_dim, False, obs_space, key
        )
        per_critic = family == "p2e_dv3"  # dict of exploration critics
        txs = {
            "world_model": instantiate(
                cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients
            ),
            "ensembles": instantiate(
                cfg.algo.ensembles.optimizer, max_grad_norm=cfg.algo.ensembles.clip_gradients
            ),
            "actor_task": instantiate(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
            "critic_task": instantiate(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients),
            "actor_exploration": instantiate(
                cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients
            ),
            ("critics_exploration" if per_critic else "critic_exploration"): instantiate(
                cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients
            ),
        }
        opt = {
            "world_model": txs["world_model"].init(params["world_model"]),
            "ensembles": txs["ensembles"].init(params["ensembles"]),
            "actor_task": txs["actor_task"].init(params["actor_task"]),
            "critic_task": txs["critic_task"].init(params["critic_task"]),
            "actor_exploration": txs["actor_exploration"].init(params["actor_exploration"]),
        }
        if per_critic:
            opt["critics_exploration"] = {
                k: txs["critics_exploration"].init(params["critics_exploration"][k]["module"])
                for k in params["critics_exploration"]
            }
        else:
            opt["critic_exploration"] = txs["critic_exploration"].init(
                params["critic_exploration"]
            )
        agent_state: Dict[str, Any] = {"params": params, "opt": opt}
        if family == "p2e_dv3":
            from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import init_moments

            agent_state["moments"] = {
                "task": init_moments(),
                "exploration": {k: init_moments() for k in params["critics_exploration"]},
            }
        train_fn = algo_mod.build_train_fn(
            world_model, actor, critic, ensemble_member, txs, cfg, fabric, actions_dim, False
        )
        pieces.update(ensemble_member=ensemble_member)
    else:
        world_model, actor, critic, params = agent_mod.build_agent(
            cfg, actions_dim, False, obs_space, key
        )
        world_tx, actor_tx, critic_tx, agent_state = algo_mod.build_optimizers_and_state(
            cfg, params
        )
        train_fn = algo_mod.build_train_fn(
            world_model, actor, critic, world_tx, actor_tx, critic_tx,
            cfg, fabric, actions_dim, False,
        )
    pieces.update(
        world_model=world_model, actor=actor, critic=critic, params=params,
        train_fn=train_fn,
    )

    T, B = int(cfg.per_rank_sequence_length), int(cfg.per_rank_batch_size)
    rng = np.random.default_rng(seed)
    batch = jax.device_put(
        {
            "rgb": jnp.asarray(rng.integers(0, 256, (T, B, 3, 64, 64)).astype(np.uint8)),
            "actions": jnp.asarray(
                np.eye(actions_dim[0], dtype=np.float32)[
                    rng.integers(0, actions_dim[0], (T, B))
                ]
            ),
            "rewards": jnp.asarray(rng.normal(size=(T, B, 1)).astype(np.float32)),
            "dones": jnp.zeros((T, B, 1), jnp.float32),
            "is_first": jnp.zeros((T, B, 1), jnp.float32),
        },
        fabric.sharding(None, fabric.data_axis),
    )
    agent_state = jax.device_put(agent_state, fabric.replicated)
    pieces["batch"] = batch

    state_box = {"state": agent_state}
    pieces["state_box"] = state_box  # live train state (Harness.state)
    tau0 = jnp.float32(1.0)

    def step_fn(i: int):
        key_i = jax.random.PRNGKey(seed + 1 + i)
        tau = tau0 if i == 0 else jnp.float32(0.02)
        if has_tau:
            out = train_fn(state_box["state"], batch, key_i, tau)
        else:
            out = train_fn(state_box["state"], batch, key_i)
        state_box["state"] = out[0]
        return out[1]

    def block_fn(metrics):
        leaf = jax.tree_util.tree_leaves(metrics)[0]
        np.asarray(leaf)

    if has_tau:
        arg_specs = shape_specs((agent_state, batch, jax.random.PRNGKey(0), tau0))
    else:
        arg_specs = shape_specs((agent_state, batch, jax.random.PRNGKey(0)))

    return Harness(family, cfg, fabric, train_fn, tuple(arg_specs), step_fn, block_fn, pieces)


# -- SAC / PPO ----------------------------------------------------------------


def _build_flat(family: str, overrides: Sequence[str], seed: int) -> Harness:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.obs.perf import shape_specs
    from sheeprl_tpu.config.instantiate import instantiate

    _module, exp, _ = FAMILIES[family]
    rng = np.random.default_rng(seed)

    if family == "sac":
        from sheeprl_tpu.algos.sac.agent import SACActor, SACCritic, build_agent_state
        from sheeprl_tpu.algos.sac.sac import build_train_fn

        cfg = _compose(exp, overrides)
        fabric = _fabric(cfg)
        obs_dim, act_dim = 8, 2  # LunarLanderContinuous-v3, the exp's env
        actor = SACActor(action_dim=act_dim, hidden_size=cfg.algo.actor.hidden_size)
        critic = SACCritic(hidden_size=cfg.algo.critic.hidden_size, num_critics=1)
        agent_state = build_agent_state(
            actor, critic, jax.random.PRNGKey(seed), int(cfg.algo.critic.n),
            obs_dim, act_dim, cfg.algo.alpha.alpha,
        )
        qf_tx = instantiate(cfg.algo.critic.optimizer)
        actor_tx = instantiate(cfg.algo.actor.optimizer)
        alpha_tx = instantiate(cfg.algo.alpha.optimizer)
        opt_states = {
            "actor": actor_tx.init(agent_state["actor"]),
            "qf": qf_tx.init(agent_state["critics"]),
            "alpha": alpha_tx.init(agent_state["log_alpha"]),
        }
        scale, bias = np.ones(act_dim, np.float32), np.zeros(act_dim, np.float32)
        train_fn = build_train_fn(
            actor, critic, actor_tx, qf_tx, alpha_tx, cfg, fabric, scale, bias,
            target_entropy=-float(act_dim),
        )
        G, B = 1, int(cfg.per_rank_batch_size)
        batch = jax.device_put(
            {
                "observations": jnp.asarray(rng.normal(size=(G, B, obs_dim)).astype(np.float32)),
                "next_observations": jnp.asarray(rng.normal(size=(G, B, obs_dim)).astype(np.float32)),
                "actions": jnp.asarray(rng.uniform(-1, 1, (G, B, act_dim)).astype(np.float32)),
                "rewards": jnp.asarray(rng.normal(size=(G, B, 1)).astype(np.float32)),
                "dones": jnp.zeros((G, B, 1), jnp.float32),
            },
            fabric.sharding(None, fabric.data_axis),
        )
        agent_state = jax.device_put(agent_state, fabric.replicated)
        opt_states = jax.device_put(opt_states, fabric.replicated)
        box = {"state": agent_state, "opt": opt_states}
        do_ema = jnp.bool_(True)

        def step_fn(i: int):
            out = train_fn(
                box["state"], box["opt"], batch, jax.random.PRNGKey(seed + 1 + i), do_ema
            )
            box["state"], box["opt"] = out[0], out[1]
            return out[2]

        arg_specs = shape_specs(
            (agent_state, opt_states, batch, jax.random.PRNGKey(0), do_ema)
        )
        pieces = {"cfg": cfg, "fabric": fabric, "actor": actor, "critic": critic,
                  "train_fn": train_fn, "batch": batch}
    else:  # ppo
        from sheeprl_tpu.algos.ppo.agent import build_agent
        from sheeprl_tpu.algos.ppo.ppo import build_update_fn

        cfg = _compose(exp, overrides + ["cnn_keys.encoder=[]", "mlp_keys.encoder=[state]"])
        fabric = _fabric(cfg)
        actions_dim, obs_dim = (2,), 4  # CartPole-v1, the exp's env
        agent = build_agent(cfg, actions_dim, False, (), ("state",))
        params = agent.init(
            jax.random.PRNGKey(seed), {"state": jnp.zeros((1, obs_dim), jnp.float32)}
        )["params"]
        tx = instantiate(cfg.algo.optimizer, max_grad_norm=cfg.algo.max_grad_norm or None)
        opt_state = tx.init(params)
        n_local = int(cfg.algo.rollout_steps) * int(cfg.env.num_envs)
        update_fn = build_update_fn(agent, tx, cfg, fabric, n_local)
        data = jax.device_put(
            {
                "state": jnp.asarray(rng.normal(size=(n_local, obs_dim)).astype(np.float32)),
                "actions": jnp.asarray(
                    rng.integers(0, actions_dim[0], (n_local, 1)).astype(np.float32)
                ),
                "logprobs": jnp.asarray(rng.normal(size=(n_local, 1)).astype(np.float32)),
                "values": jnp.asarray(rng.normal(size=(n_local, 1)).astype(np.float32)),
                "advantages": jnp.asarray(rng.normal(size=(n_local, 1)).astype(np.float32)),
                "returns": jnp.asarray(rng.normal(size=(n_local, 1)).astype(np.float32)),
            },
            fabric.replicated if cfg.buffer.share_data else fabric.data_sharding,
        )
        params = jax.device_put(params, fabric.replicated)
        opt_state = jax.device_put(opt_state, fabric.replicated)
        box = {"params": params, "opt": opt_state}
        clip, ent = jnp.float32(cfg.algo.clip_coef), jnp.float32(cfg.algo.ent_coef)

        def step_fn(i: int):
            out = update_fn(
                box["params"], box["opt"], data, jax.random.PRNGKey(seed + 1 + i), clip, ent
            )
            box["params"], box["opt"] = out[0], out[1]
            return out[2]

        train_fn = update_fn
        arg_specs = shape_specs(
            (params, opt_state, data, jax.random.PRNGKey(0), clip, ent)
        )
        pieces = {"cfg": cfg, "fabric": fabric, "agent": agent, "train_fn": update_fn,
                  "batch": data}

    def block_fn(out):
        leaf = jax.tree_util.tree_leaves(out)[0]
        np.asarray(leaf)

    return Harness(
        family, pieces["cfg"], pieces["fabric"], pieces["train_fn"],
        tuple(arg_specs), step_fn, block_fn, pieces,
    )
