"""Device-time profiling and roofline attribution (howto/profiling.md).

The missing half of the PR-1/PR-4 telemetry plane: those measure *host*
wall-time per phase; this package measures where the **device** time goes
and what binds it. Four pieces:

- :mod:`~sheeprl_tpu.obs.prof.xplane` — a self-contained parser for the
  ``*.xplane.pb`` traces ``jax.profiler`` writes (no tensorflow import; the
  proto wire format is decoded directly). Attributes profiled device time to
  compiled XLA modules — per-module executions, total ms, ms/exec — with a
  host-plane fallback so CPU runs profile too, and maps module names onto
  the framework's phase names (train step, acting, rollout scan, staging).
- :mod:`~sheeprl_tpu.obs.prof.roofline` — combines ``cost_analysis()``
  FLOPs + bytes-accessed with measured device time into per-module MFU,
  achieved bandwidth, and a compute-bound / memory-bound / dispatch-bound
  verdict against a device-peak registry (CPU fallback included so the
  analysis runs everywhere).
- :mod:`~sheeprl_tpu.obs.prof.capture` — the in-run capture scheduler:
  ``profile_tick`` (called by every entrypoint at its log boundary, linted
  by ``tools/lint_telemetry.py``) opens a ``jax.profiler`` window every
  ``metric.telemetry.profile.every_n_steps`` policy steps, auto-parses it,
  and folds ``device_ms_per_step`` / ``mfu_device_pct`` /
  ``roofline_verdict`` into ``telemetry.json`` and ``live.json``.
- :mod:`~sheeprl_tpu.obs.prof.harness` — one builder of a family's real
  train step on synthetic data (the setup previously copy-pasted across
  ``bench_dreamer.py``, ``tools/profile_step.py`` and the ``diag_dv3_*``
  one-offs), used by ``tools/roofline_report.py`` to produce the per-family
  binding-constraint table.
"""

from sheeprl_tpu.obs.prof.capture import (
    StepProfiler,
    parse_and_fold,
    profile_tick,
    try_begin_capture,
    end_capture,
)
from sheeprl_tpu.obs.prof.roofline import (
    DEVICE_PEAKS,
    cost_bytes,
    cost_of,
    detect_peaks,
    roofline_analyze,
)
from sheeprl_tpu.obs.prof.xplane import (
    find_xplane,
    load_xspace,
    phase_of,
    summarize,
    summarize_space,
)

__all__ = [
    "DEVICE_PEAKS",
    "StepProfiler",
    "cost_bytes",
    "cost_of",
    "detect_peaks",
    "end_capture",
    "find_xplane",
    "load_xspace",
    "parse_and_fold",
    "phase_of",
    "profile_tick",
    "roofline_analyze",
    "summarize",
    "summarize_space",
    "try_begin_capture",
]
