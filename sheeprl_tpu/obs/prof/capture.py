"""In-run device-profile capture: scheduled windows, auto-parse, fold-in.

``tools/profile_step.py`` could always capture a trace — by hand, offline,
for one hard-wired workload. This module makes the same capture a scheduled
part of any run: every ``metric.telemetry.profile.every_n_steps`` policy
steps (default off) the :class:`StepProfiler` opens the PR-4
``profiler_capture`` window, bounds it by ``profile.window_s`` (a timer
thread stops the trace so a slow log cadence cannot produce a gigabyte
xplane), parses it with :mod:`~sheeprl_tpu.obs.prof.xplane`, runs the
:mod:`~sheeprl_tpu.obs.prof.roofline` analysis against the registered train
cost, and folds ``device_ms_per_step`` / ``mfu_device_pct`` /
``roofline_verdict`` into ``telemetry.json`` + ``live.json`` (plus a
per-capture ``telemetry/prof/capture_<step>.json`` artifact).

Entrypoints drive it through one call — :func:`profile_tick`, placed at the
same log boundary as ``log_sps_metrics`` and required there by
``tools/lint_telemetry.py``. Everything is a no-op when telemetry or the
profile group is off, and a failed capture/parse can never take a run down.

``jax.profiler`` allows one active trace per process, and the PR-4 flight
recorder opens capture windows of its own: both now arbitrate through
:func:`try_begin_capture` / :func:`end_capture` so the two can never race a
``start_trace`` into an already-tracing runtime.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

__all__ = [
    "StepProfiler",
    "end_capture",
    "parse_and_fold",
    "profile_tick",
    "try_begin_capture",
]

# one jax.profiler trace per process: shared by StepProfiler and the
# flight recorder's anomaly capture window
_CAPTURE_LOCK = threading.Lock()
_CAPTURE_ACTIVE = False


def try_begin_capture() -> bool:
    """Claim the process-wide profiler slot; False when a capture is live."""
    global _CAPTURE_ACTIVE
    with _CAPTURE_LOCK:
        if _CAPTURE_ACTIVE:
            return False
        _CAPTURE_ACTIVE = True
        return True


def end_capture() -> None:
    global _CAPTURE_ACTIVE
    with _CAPTURE_LOCK:
        _CAPTURE_ACTIVE = False


def analyze_trace(
    trace_dir: str,
    flops_per_step: Optional[float] = None,
    bytes_per_step: Optional[float] = None,
    world_size: int = 1,
    dispatches_per_step: int = 1,
    peaks: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Parse one capture directory and run the roofline on its train module.

    ``flops_per_step`` / ``bytes_per_step`` are per train-step *unit* (the
    ``set_train_cost`` convention: program cost × dispatches_per_step /
    world_size, with the step counter advancing by world_size per training
    block), so per-dispatch cost is ``× world_size / dispatches_per_step``
    and per-unit device time is ``× dispatches_per_step / world_size``.
    """
    from sheeprl_tpu.obs.prof.roofline import roofline_analyze
    from sheeprl_tpu.obs.prof.xplane import find_xplane, load_xspace, summarize_space

    summary = summarize_space(load_xspace(find_xplane(trace_dir)))
    train = summary["train_module"]
    rec = summary["modules"].get(train) if train else None
    ms_per_exec = rec["ms_per_exec"] if rec else None
    ws = max(int(world_size), 1)
    dps = max(int(dispatches_per_step), 1)
    roofline = roofline_analyze(
        flops_per_exec=flops_per_step * ws / dps if flops_per_step else None,
        bytes_per_exec=bytes_per_step * ws / dps if bytes_per_step else None,
        device_ms_per_exec=ms_per_exec,
        busy_frac=summary["busy_frac"],
        peaks=peaks,
    )
    top = sorted(
        summary["modules"].items(), key=lambda kv: kv[1]["total_ms"], reverse=True
    )[:8]
    # compute-vs-comms split of the profiled device time: collective-op
    # self-time (all-reduce/all-gather/... HLO categories, obs/prof/xplane)
    # attributed per train-step unit. Collectives run inside the train
    # program, so the per-exec share divides by the train module's execs.
    device_ms = round(ms_per_exec * dps / ws, 3) if ms_per_exec is not None else None
    comms_total = summary.get("comms_ms_total")
    comms_ms = compute_ms = comms_by_kind = None
    if device_ms is not None and comms_total is not None and rec and rec["execs"]:
        comms_ms = round(comms_total / rec["execs"] * dps / ws, 4)
        compute_ms = round(max(device_ms - comms_ms, 0.0), 4)
        # same per-step attribution, split by collective category: under
        # parameter sharding the gradient all-reduce and the parameter
        # all-gather/reduce-scatter scale with different byte volumes, so
        # the binding-constraint story needs them reported separately
        by_kind = summary.get("comms_ms_by_kind")
        if by_kind:
            comms_by_kind = {
                kind: round(v / rec["execs"] * dps / ws, 4)
                for kind, v in by_kind.items()
            }
    return {
        "trace_dir": trace_dir,
        "source": summary["source"],
        "train_module": train,
        "device_ms_per_step": device_ms,
        "comms_ms_per_step": comms_ms,
        "comms_ms_by_kind_per_step": comms_by_kind,
        "compute_ms_per_step": compute_ms,
        "mfu_device_pct": roofline["mfu_pct"],
        "achieved_gbps": roofline["achieved_gbps"],
        "bandwidth_util_pct": roofline["bandwidth_util_pct"],
        "arithmetic_intensity": roofline["arithmetic_intensity"],
        "roofline_verdict": roofline["verdict"],
        "peaks": roofline["peaks"],
        "window_ms": summary["window_ms"],
        "busy_frac": summary["busy_frac"],
        "modules": {
            name: {
                "phase": m["phase"],
                "execs": m["execs"],
                "ms_per_exec": round(m["ms_per_exec"], 3),
                "total_ms": round(m["total_ms"], 3),
            }
            for name, m in top
        },
    }


def parse_and_fold(
    trace_dir: str, telemetry=None, world_size: Optional[int] = None
) -> Optional[Dict[str, Any]]:
    """Best-effort: analyze a finished capture and fold it into ``telemetry``
    (used by the flight recorder after its anomaly capture window). Returns
    the record, or None when the trace is unreadable.

    World size and peak overrides come from the telemetry when not given —
    ``profile_tick`` records the caller's world_size, and the StepProfiler
    carries the ``profile.peak_*`` config — so an anomaly capture scales
    and classifies identically to a scheduled one."""
    prof = getattr(telemetry, "prof", None)
    try:
        from sheeprl_tpu.obs.prof.roofline import detect_peaks

        record = analyze_trace(
            trace_dir,
            flops_per_step=getattr(telemetry, "flops_per_train_step", None),
            bytes_per_step=getattr(telemetry, "bytes_per_train_step", None),
            world_size=world_size or getattr(telemetry, "last_world_size", 1),
            dispatches_per_step=getattr(telemetry, "dispatches_per_train_step", 1),
            peaks=detect_peaks(
                getattr(prof, "peak_tflops", None), getattr(prof, "peak_gbps", None)
            ),
        )
    except Exception:
        return None
    if telemetry is not None:
        telemetry.record_prof(record)
    return record


class StepProfiler:
    """Scheduled in-run capture windows, parsed and folded as they land.

    State machine (one capture at a time): ``tick`` starts a capture when
    ``policy_step`` crosses the next schedule point; a timer thread bounds
    the window at ``window_s`` (stopping the trace exactly the way the
    flight recorder's capture window does); whichever of the timer or the
    next ``tick`` runs first finalizes — stop, parse, roofline, fold. A
    short run that never reaches another boundary is finalized by
    :meth:`close` from ``Telemetry.finalize``, so a profiled run always
    lands its numbers.
    """

    def __init__(self, pcfg: Dict[str, Any], telemetry):
        pcfg = dict(pcfg or {})
        self.every_n_steps = int(pcfg.get("every_n_steps", 0) or 0)
        ws = pcfg.get("window_s", 10.0)
        #: 0/null = no timer cap — the window runs to the next log boundary
        self.window_s = float(ws) if ws else 0.0
        mc = pcfg.get("max_captures", 4)
        self.max_captures = int(mc) if mc is not None else 4
        self.peak_tflops = pcfg.get("peak_tflops") or None
        self.peak_gbps = pcfg.get("peak_gbps") or None
        self.enabled = self.every_n_steps > 0
        self.telemetry = telemetry
        self.captures = 0
        self.failed = 0
        self.last: Optional[Dict[str, Any]] = None
        self._next_at = self.every_n_steps
        self._lock = threading.Lock()
        self._active: Optional[Dict[str, Any]] = None
        self._parse_threads: list = []

    # -- the entrypoint hook --------------------------------------------------

    def tick(self, policy_step: int, world_size: int = 1) -> None:
        if not self.enabled:
            return
        if self._active is not None:
            self._finalize()
            return
        # failed attempts count toward the cap too: a persistently
        # unparseable trace must not re-open profiler windows all run long
        if policy_step >= self._next_at and self.captures + self.failed < self.max_captures:
            self._start(policy_step, world_size)
            # schedule strictly forward even if boundaries lag the cadence
            while self._next_at <= policy_step:
                self._next_at += self.every_n_steps

    def _start(self, policy_step: int, world_size: int) -> None:
        run_dir = getattr(self.telemetry, "run_dir", None)
        if run_dir is None or not try_begin_capture():
            return
        out_dir = os.path.join(run_dir, "telemetry", "prof", f"step_{policy_step}")
        try:
            import jax

            jax.profiler.start_trace(os.path.abspath(out_dir))
        except Exception:
            end_capture()
            return
        timer = None
        if self.window_s > 0:
            timer = threading.Timer(self.window_s, self._stop_trace)
            timer.daemon = True
        with self._lock:
            self._active = {
                "dir": out_dir,
                "step": int(policy_step),
                "world_size": max(int(world_size), 1),
                "timer": timer,
                "stopped": False,
                # set once stop_trace has RETURNED (the xplane is on disk):
                # a finalize racing the timer thread must not parse earlier
                "stop_done": threading.Event(),
            }
        if timer is not None:
            timer.start()

    def _stop_trace(self) -> bool:
        """Stop the live trace exactly once; True when this call stopped it.

        Releases the process-wide capture guard as soon as the trace is
        stopped — the parse only needs the directory, and holding the slot
        until the next tick would refuse a flight-recorder anomaly window
        for minutes on a slow log cadence."""
        with self._lock:
            active = self._active
            if active is None or active["stopped"]:
                return False
            active["stopped"] = True
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass
        end_capture()
        active["stop_done"].set()
        return True

    def _finalize(self, wait: bool = False) -> None:
        self._stop_trace()  # no-op (incl. the guard release) if the timer won
        with self._lock:
            active, self._active = self._active, None
        if active is None:
            return
        if active["timer"] is not None:
            active["timer"].cancel()

        def _work() -> None:
            # if the timer thread won the stop race, its stop_trace may still
            # be serializing the xplane — parsing before it lands loses the
            # capture
            active["stop_done"].wait(timeout=30.0)
            record = parse_and_fold(
                active["dir"], self.telemetry, world_size=active["world_size"]
            )
            with self._lock:
                if record is None:
                    self.failed += 1
                else:
                    record["step"] = active["step"]  # _prof_last holds this dict
                    self.captures += 1
                    self.last = record
            if record is None:
                return
            try:
                from sheeprl_tpu.obs.live import atomic_write_json

                atomic_write_json(
                    os.path.join(os.path.dirname(active["dir"]), f"capture_{active['step']}.json"),
                    record,
                )
            except OSError:
                pass  # a full disk must not take the run down

        if wait:
            _work()
            return
        # a big trace decodes in pure Python for seconds — off the training
        # thread (the flight recorder's capture does the same); close() joins
        thread = threading.Thread(target=_work, name="obs-prof-parse", daemon=True)
        with self._lock:
            self._parse_threads = [t for t in self._parse_threads if t.is_alive()]
            self._parse_threads.append(thread)
        thread.start()

    def close(self) -> None:
        """Finalize any in-flight capture and join EVERY in-flight parse
        (Telemetry.finalize calls this before assembling the summary — a
        slow earlier parse must land its numbers too, not just the newest)."""
        if self._active is not None:
            self._finalize(wait=True)
        with self._lock:
            threads = list(self._parse_threads)
        for thread in threads:
            if thread.is_alive():
                thread.join(timeout=60.0)


def profile_tick(*, policy_step: int, world_size: int = 1) -> None:
    """The per-entrypoint profiling hook: advance the in-run capture
    scheduler. Call at the same log boundary as ``log_sps_metrics``
    (``tools/lint_telemetry.py`` enforces the pairing); a no-op unless
    ``metric.telemetry.profile.every_n_steps`` is set."""
    from sheeprl_tpu.obs.telemetry import get_telemetry

    telemetry = get_telemetry()
    if telemetry is None:
        return
    # remembered so an anomaly (flight-recorder) capture parsed outside any
    # tick scales per-unit numbers with the run's real world size
    telemetry.last_world_size = max(int(world_size), 1)
    prof = getattr(telemetry, "prof", None)
    if prof is not None:
        prof.tick(policy_step, world_size)
