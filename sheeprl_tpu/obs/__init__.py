"""Runtime telemetry: phase spans, device/transfer/recompile counters, and a
run-health monitor.

The observability layer the ROADMAP's data-path rounds are judged against —
it *measures* the host→HBM staging path, XLA recompiles, HBM occupancy, and
per-phase wall time instead of inferring them from wall-clock deltas. Four
pieces (see ``howto/telemetry.md``):

- :mod:`~sheeprl_tpu.obs.spans` — Chrome trace-event spans layered on the
  global ``timer`` registry, mirrored into XLA profiles;
- :mod:`~sheeprl_tpu.obs.counters` — host→HBM byte accounting, a
  ``jax.monitoring`` recompile listener, and a device-memory poller;
- :mod:`~sheeprl_tpu.obs.health` — NaN/inf guards on logged losses and a
  stall watchdog for decoupled player↔trainer threads;
- :mod:`~sheeprl_tpu.obs.perf` — the shared ``Time/sps_*`` / ``Perf/mfu``
  gauge plumbing every entrypoint logs through;
- :mod:`~sheeprl_tpu.obs.hist` — mergeable log-bucket streaming histograms
  of every span duration (per-phase ``p50/p95/p99``);
- :mod:`~sheeprl_tpu.obs.live` — the live plane: periodic atomic
  ``telemetry/live.json`` snapshots, an optional Prometheus endpoint, and
  the anomaly-triggered flight recorder;
- :mod:`~sheeprl_tpu.obs.learn` — learning-health: in-jit training-dynamics
  probes (grad/param/update norms, clip fraction, non-finite counts) and the
  divergence early-warning sentinel (``howto/learning_health.md``);
- :mod:`~sheeprl_tpu.obs.prof` — device-time profiling: in-run xplane
  capture + parsing, per-module attribution, and the roofline
  (MFU / bandwidth / binding-constraint) accounting
  (``howto/profiling.md``).

Everything is configured by the ``metric.telemetry`` config group and
defaults to off; disabled, the instrumented code paths reduce to the plain
``timer`` registry with no extra file handles, threads, or device syncs.
"""

from sheeprl_tpu.obs.counters import (
    Counters,
    DevicePoller,
    add_act_dispatches,
    add_ckpt_blocked_ms,
    add_ckpt_write,
    add_env_async_steps,
    add_env_degraded,
    add_env_worker_restart,
    add_h2d_bytes,
    add_plane_player_restart,
    add_plane_slabs,
    add_prefetch,
    add_ring_gather,
    add_rollout_burst,
    count_h2d,
    device_memory_stats,
    note_plane_policy_version,
    set_shard_footprint,
    staged_device_put,
    tree_nbytes,
)
from sheeprl_tpu.obs.dist.comms import collective_span, pmean, psum
from sheeprl_tpu.obs.dist.staleness import StalenessTracker
from sheeprl_tpu.obs.health import NonFiniteGuard, StallWatchdog
from sheeprl_tpu.obs.hist import HistogramSet, StreamingHist
from sheeprl_tpu.obs.learn import (
    LearnSentinel,
    learn_probes,
    observe_probes,
    probes_enabled,
    split_probes,
)
from sheeprl_tpu.obs.live import (
    FlightRecorder,
    LiveExporter,
    PromServer,
    profiler_capture,
    prometheus_text,
)
from sheeprl_tpu.obs.perf import (
    PEAK_TFLOPS_BF16,
    LoopProbe,
    cost_flops,
    log_sps_metrics,
    mfu_pct,
    register_train_cost,
    shape_specs,
)
from sheeprl_tpu.obs.prof.capture import profile_tick
from sheeprl_tpu.obs.spans import TraceWriter, get_tracer, set_tracer, span
from sheeprl_tpu.obs.telemetry import (
    Telemetry,
    finalize_telemetry,
    get_telemetry,
    setup_telemetry,
)

__all__ = [
    "Counters",
    "DevicePoller",
    "FlightRecorder",
    "HistogramSet",
    "LearnSentinel",
    "LiveExporter",
    "LoopProbe",
    "NonFiniteGuard",
    "PEAK_TFLOPS_BF16",
    "PromServer",
    "StalenessTracker",
    "StallWatchdog",
    "StreamingHist",
    "Telemetry",
    "TraceWriter",
    "add_act_dispatches",
    "add_ckpt_blocked_ms",
    "add_ckpt_write",
    "add_env_async_steps",
    "add_env_degraded",
    "add_env_worker_restart",
    "add_h2d_bytes",
    "add_plane_player_restart",
    "add_plane_slabs",
    "add_prefetch",
    "add_ring_gather",
    "add_rollout_burst",
    "collective_span",
    "count_h2d",
    "cost_flops",
    "device_memory_stats",
    "finalize_telemetry",
    "get_telemetry",
    "get_tracer",
    "learn_probes",
    "log_sps_metrics",
    "mfu_pct",
    "note_plane_policy_version",
    "observe_probes",
    "probes_enabled",
    "set_shard_footprint",
    "pmean",
    "profile_tick",
    "profiler_capture",
    "prometheus_text",
    "psum",
    "register_train_cost",
    "set_tracer",
    "setup_telemetry",
    "shape_specs",
    "span",
    "split_probes",
    "staged_device_put",
    "tree_nbytes",
]
