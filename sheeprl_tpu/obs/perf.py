"""SPS / MFU gauges — the one implementation every entrypoint logs through.

Before this module the ``Time/sps_*`` block was copy-pasted across all 17
algorithm entrypoints and MFU lived only in ``bench_dreamer.py``; the copies
had already drifted (bare division vs ``max(..., 1e-9)`` guards).
:func:`log_sps_metrics` is now the single computation — entrypoints call it
at their log boundary and ``tools/lint_telemetry.py`` fails CI if one grows
its own ``Time/sps_`` literal again. The benches import the same FLOPs/MFU
helpers, so benchmark numbers and run telemetry cannot disagree on the
formula.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

__all__ = [
    "LoopProbe",
    "PEAK_TFLOPS_BF16",
    "cost_flops",
    "log_sps_metrics",
    "mfu_pct",
    "register_train_cost",
    "shape_specs",
]

#: TPU v5e single-chip bf16 peak — the default MFU denominator
#: (``metric.telemetry.peak_tflops`` overrides; 32-true programs are measured
#: against the same bf16 peak so numbers stay comparable across precisions).
PEAK_TFLOPS_BF16 = 197.0


class LoopProbe:
    """Env-gated per-phase wall-time probe for latency-dominated hot loops.

    ``SHEEPRL_LOOP_TRACE=1`` prints the mean per-iteration wall time of each
    ``lap``-delimited slice every ``every`` iterations — the remote-attached
    device loop is latency-dominated and the TB timers can't see through
    async dispatch, so this is the ground truth for where a slow loop spends
    its time. The algorithms use this instead of hand-rolled
    ``time.perf_counter()`` deltas (``tools/lint_telemetry.py`` rejects those
    in ``sheeprl_tpu/algos/`` so loop accounting stays in one place); when
    the env var is unset every call is a single attribute check.
    """

    __slots__ = ("enabled", "every", "_acc", "_n", "_t")

    def __init__(self, every: int = 50, env_var: str = "SHEEPRL_LOOP_TRACE"):
        self.enabled = os.environ.get(env_var) not in (None, "", "0")
        self.every = int(every)
        self._acc: Dict[str, float] = {}
        self._n = 0
        self._t = 0.0

    def mark(self) -> None:
        """Start (or restart) the slice clock — call at the top of the loop."""
        if self.enabled:
            self._t = time.perf_counter()

    def lap(self, name: str) -> None:
        """Account the time since the last mark/lap to ``name``."""
        if self.enabled:
            t = time.perf_counter()
            self._acc[name] = self._acc.get(name, 0.0) + (t - self._t)
            self._t = t

    def tick(self, update: int) -> None:
        """End of one iteration; prints and resets every ``every`` calls."""
        if not self.enabled:
            return
        self._n += 1
        if self._n % self.every == 0:
            parts = " ".join(
                f"{k}={v / self.every * 1000:.0f}ms" for k, v in sorted(self._acc.items())
            )
            print(f"[loop-trace] update={update} mean/iter: {parts}", flush=True)
            self._acc.clear()


def cost_flops(compiled) -> float:
    """FLOPs of a compiled XLA module per ``Compiled.cost_analysis()``.

    Caveat inherited by every consumer: XLA counts a while-loop *body once*
    regardless of trip count, so scan-heavy programs under-report (the
    Dreamer benches add per-family scan-body corrections on top of this).
    """
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    return float(ca.get("flops", 0.0))


def shape_specs(tree: Any) -> Any:
    """Abstract (shape, dtype) specs of a pytree of arrays — safe to keep
    around after the concrete (possibly donated) buffers are gone."""
    import jax
    import numpy as np

    def spec(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
        return jax.ShapeDtypeStruct((), np.asarray(x).dtype)

    return jax.tree_util.tree_map(spec, tree)


def _reference_twin(jit_fn):
    """A reference-tier twin of ``jit_fn`` for cost analysis, or None.

    When a fused recurrent-core tier is active (``sheeprl_tpu/kernels``) the
    train program may contain Pallas custom calls, which XLA's cost model
    scores as zero FLOPs, or padded-lane matmuls (600→640), which it scores
    as *more* FLOPs than the model actually defines. Either way the
    registered cost — and with it MFU and the roofline numerators — would
    change with the kernel tier. Model FLOPs are a property of the model,
    not of the kernel strategy (the PaLM-MFU convention), so when a fused
    tier is active we lower a twin program instead: a fresh ``jax.jit`` of
    the wrapped python body, traced under
    :func:`~sheeprl_tpu.kernels.reference_cost_mode` so the kernel
    dispatchers take the reference path at trace time.
    """
    from sheeprl_tpu import kernels

    if not kernels.fused_active():
        return None
    raw = getattr(jit_fn, "__wrapped__", None)
    if raw is None:
        return None
    import jax

    def _ref(*args):
        with kernels.reference_cost_mode():
            return raw(*args)

    return jax.jit(_ref)


def register_train_cost(
    telemetry, jit_fn, *specs, world_size: int = 1, dispatches_per_step: int = 1
) -> None:
    """One AOT cost analysis of the train program, registered with the run
    telemetry per train-step *unit*.

    The step counter advances by ``world_size`` per *training block*, which
    dispatches the analyzed program ``dispatches_per_step`` times (1 for the
    fused-burst families — DV3, SAC, PPO; ``per_rank_gradient_steps`` for
    the families that loop a single-gradient-step program — DV1, DV2, P2E).
    Registered cost = program cost × dispatches / world_size, so
    ``flops_per_train_step × Δtrain_step`` is the per-device work actually
    executed — the MFU numerator against the single-chip peak, and (with
    bytes accessed) the roofline numerators for the in-run profiler
    (``obs/prof``). Entrypoints call this once, gated on
    :meth:`~sheeprl_tpu.obs.telemetry.Telemetry.needs_train_flops`; a
    backend without a cost model records the attempt and stays quiet.
    """
    if telemetry is None:
        return
    from sheeprl_tpu.obs.prof.roofline import cost_of

    cost = None
    ref_fn = _reference_twin(jit_fn)
    if ref_fn is not None:
        cost = cost_of(ref_fn, *specs)
    if not (cost and cost.get("flops")):
        # no fused tier active, or the twin couldn't lower (e.g. the train
        # callable isn't a plain jit wrapper): fall back to the program as-is
        cost = cost_of(jit_fn, *specs)
    ws = max(int(world_size), 1)
    dps = max(int(dispatches_per_step), 1)
    if cost and cost.get("flops"):
        telemetry.set_train_cost(
            cost["flops"] * dps / ws,
            (cost.get("bytes_accessed") or 0.0) * dps / ws or None,
            dispatches_per_step=dps,
        )
    else:
        telemetry.set_train_cost(None, None)


def mfu_pct(
    flops_per_step: Optional[float],
    steps: float,
    seconds: Optional[float],
    peak_tflops: float = PEAK_TFLOPS_BF16,
) -> Optional[float]:
    """Model FLOPs utilization in percent, or None when unmeasurable."""
    if not flops_per_step or not seconds or seconds <= 0 or steps <= 0 or peak_tflops <= 0:
        return None
    return round(flops_per_step * steps / seconds / (peak_tflops * 1e12) * 100.0, 3)


def log_sps_metrics(
    logger,
    *,
    policy_step: int,
    last_log: int,
    train_step: int = 0,
    last_train: int = 0,
    world_size: int = 1,
    action_repeat: int = 1,
) -> Dict[str, float]:
    """Compute the standard rate gauges from the global timer registry, log
    them, and feed the run telemetry.

    Reads-and-resets the registry (the ``timer.compute()`` contract), so call
    exactly once per log boundary. Returns the gauges that were logged:
    ``Time/sps_train`` (train steps per second of timed train wall),
    ``Time/sps_env_interaction`` (per-process env steps × action_repeat per
    second of timed interaction wall), and — when the algorithm registered
    its per-train-step FLOPs with the telemetry — ``Perf/mfu``.
    """
    from sheeprl_tpu.obs.telemetry import get_telemetry
    from sheeprl_tpu.utils.timer import timer

    telemetry = get_telemetry()
    if timer.disabled:
        # reachable only under metric.disable_timer=true (every call site is
        # log_level-gated, and log_level=0 implies disabled timers): keep the
        # telemetry step totals accurate even without rate gauges. Fully
        # quiet runs (log_level=0) never reach a log boundary at all — their
        # telemetry.json reports the step/rate fields as null by design.
        if telemetry is not None:
            telemetry.record_window(
                policy_steps=policy_step - last_log,
                train_steps=train_step - last_train,
            )
        return {}
    timer_metrics = timer.compute()
    train_s = timer_metrics.get("Time/train_time")
    env_s = timer_metrics.get("Time/env_interaction_time")
    train_steps = train_step - last_train
    policy_steps = policy_step - last_log

    gauges: Dict[str, float] = {}
    if train_s:
        gauges["Time/sps_train"] = train_steps / max(train_s, 1e-9)
    if env_s:
        gauges["Time/sps_env_interaction"] = (
            policy_steps / world_size * action_repeat
        ) / max(env_s, 1e-9)

    if telemetry is not None:
        telemetry.record_window(
            policy_steps=policy_steps,
            train_steps=train_steps,
            env_seconds=env_s or 0.0,
            train_seconds=train_s or 0.0,
            stage_seconds=timer_metrics.get("Time/stage_h2d_time", 0.0),
        )
        mfu = mfu_pct(
            telemetry.flops_per_train_step,
            train_steps,
            train_s,
            telemetry.peak_tflops,
        )
        if mfu is not None:
            gauges["Perf/mfu"] = mfu

    if logger is not None and gauges:
        logger.log_metrics(gauges, policy_step)
    return gauges
