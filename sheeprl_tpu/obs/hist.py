"""Mergeable log-bucket streaming histograms for span durations.

The PR-1 telemetry reports whole-run *averages* (``sps``, ``env_seconds`` /
``train_seconds``), which hide exactly what a production operator needs to
see: tail latency. A recompile storm that doubles one train step in twenty,
an env worker that hiccups every few hundred interactions, a staging burst
that occasionally waits out a full prefetch miss — all of them vanish into a
mean. :class:`StreamingHist` records every span duration into logarithmic
buckets (constant *relative* resolution, ~9% per bucket), so ``p50/p95/p99``
per phase costs a few hundred bytes of memory and one ``log2`` per
observation, never a sample array.

Bucketing is a pure function of the value (``floor(log2(v) × 8)``), which
makes histograms **exactly mergeable**: the same observations recorded on
any split of threads/ranks/processes produce bit-identical bucket maps, and
:meth:`StreamingHist.merge` is plain per-bucket addition. Decoupled
player↔trainer runs merge their per-role tails losslessly — the per-role
p99 is precisely what the stall watchdog's binary alive/wedged view cannot
show.

Like the counter module, everything is a no-op until ``setup_telemetry``
calls :func:`install`: with no set installed, :func:`observe` is one global
read and a ``None`` check, so instrumented span exits cost nothing in
un-instrumented runs (the acceptance invariant: no histogram allocation
exists when telemetry is off).
"""

from __future__ import annotations

import math
import threading
from typing import Any, Callable, Dict, Iterable, Optional

__all__ = [
    "HistogramSet",
    "StreamingHist",
    "install",
    "installed",
    "observe",
]

#: buckets per power of two — 8 gives ~9% relative resolution per bucket
#: (2**(1/8) ≈ 1.0905), plenty for latency percentiles at ~100 B/decade
BUCKETS_PER_OCTAVE = 8
_LOG_SCALE = float(BUCKETS_PER_OCTAVE)

_HISTS: Optional["HistogramSet"] = None


def install(hists: Optional["HistogramSet"]) -> None:
    """Activate (or with ``None`` deactivate) the run's histogram set."""
    global _HISTS
    _HISTS = hists


def installed() -> Optional["HistogramSet"]:
    return _HISTS


def observe(name: str, seconds: float) -> None:
    """Record one span duration (no-op when telemetry/histograms are off)."""
    h = _HISTS
    if h is not None:
        h.observe(name, seconds)


def bucket_index(value: float) -> int:
    """Deterministic log bucket of a positive value."""
    return math.floor(math.log2(value) * _LOG_SCALE)


def bucket_bounds(index: int) -> tuple:
    """``[lo, hi)`` value bounds of a bucket index."""
    return (2.0 ** (index / _LOG_SCALE), 2.0 ** ((index + 1) / _LOG_SCALE))


class StreamingHist:
    """A streaming histogram over log-spaced buckets.

    Sparse (``{bucket_index: count}``), thread-safe, and exactly mergeable:
    bucket indices depend only on the observed values, so any partition of
    the same observations merges back to the identical histogram. Values
    ``<= 0`` (a clock that did not advance) land in a dedicated zero bucket
    and count toward ``n`` but sit below every positive bucket for
    quantiles.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.counts: Dict[int, int] = {}
        self.zero = 0
        self.n = 0
        self.sum = 0.0
        self.max = 0.0

    def record(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self.n += 1
            if value <= 0.0:
                self.zero += 1
                return
            idx = bucket_index(value)
            self.counts[idx] = self.counts.get(idx, 0) + 1
            self.sum += value
            if value > self.max:
                self.max = value

    def record_many(self, values) -> None:
        """Record a whole array of observations vectorized (one ``np.log2``
        over the batch instead of a Python call per element — the
        staleness tracker observes every sampled row's age this way).
        Bucketing is bit-identical to :meth:`record`: both compute
        ``floor(log2(v) × 8)`` in float64."""
        import numpy as np

        vals = np.asarray(values, dtype=np.float64).reshape(-1)
        if vals.size == 0:
            return
        pos = vals[vals > 0.0]
        n_zero = int(vals.size - pos.size)
        if pos.size:
            idxs = np.floor(np.log2(pos) * _LOG_SCALE).astype(np.int64)
            uniq, counts = np.unique(idxs, return_counts=True)
            total, mx = float(pos.sum()), float(pos.max())
        else:
            uniq = counts = ()
            total, mx = 0.0, 0.0
        with self._lock:
            self.n += int(vals.size)
            self.zero += n_zero
            for idx, c in zip(uniq, counts):
                self.counts[int(idx)] = self.counts.get(int(idx), 0) + int(c)
            self.sum += total
            if mx > self.max:
                self.max = mx

    def quantile(self, q: float) -> Optional[float]:
        """The ``q``-quantile (0..1) as the geometric mid of its bucket."""
        with self._lock:
            return self._quantile_locked(q)

    def _quantile_locked(self, q: float) -> Optional[float]:
        if self.n == 0:
            return None
        # rank among all observations; the zero bucket sorts first
        rank = q * self.n
        if rank <= self.zero:
            return 0.0
        seen = self.zero
        for idx in sorted(self.counts):
            seen += self.counts[idx]
            if seen >= rank:
                lo, hi = bucket_bounds(idx)
                return math.sqrt(lo * hi)
        lo, hi = bucket_bounds(max(self.counts)) if self.counts else (0.0, 0.0)
        return math.sqrt(lo * hi) if self.counts else 0.0

    def percentiles(self) -> Dict[str, Any]:
        """The reporting dict: ``p50/p95/p99`` in milliseconds plus exact
        ``count`` / ``mean_ms`` / ``max_ms`` (the extremes are tracked
        exactly, not bucketed)."""
        with self._lock:
            n_pos = self.n - self.zero
            return {
                "count": self.n,
                "p50_ms": _ms(self._quantile_locked(0.50)),
                "p95_ms": _ms(self._quantile_locked(0.95)),
                "p99_ms": _ms(self._quantile_locked(0.99)),
                "mean_ms": _ms(self.sum / n_pos) if n_pos else 0.0,
                "max_ms": _ms(self.max),
            }

    # -- merge / serialization ----------------------------------------------

    def merge(self, other: "StreamingHist") -> "StreamingHist":
        with other._lock:
            counts = dict(other.counts)
            zero, n, total, mx = other.zero, other.n, other.sum, other.max
        with self._lock:
            for idx, c in counts.items():
                self.counts[idx] = self.counts.get(idx, 0) + c
            self.zero += zero
            self.n += n
            self.sum += total
            if mx > self.max:
                self.max = mx
        return self

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "buckets": {str(k): v for k, v in sorted(self.counts.items())},
                "zero": self.zero,
                "n": self.n,
                "sum": self.sum,
                "max": self.max,
                "buckets_per_octave": BUCKETS_PER_OCTAVE,
            }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "StreamingHist":
        if int(d.get("buckets_per_octave", BUCKETS_PER_OCTAVE)) != BUCKETS_PER_OCTAVE:
            raise ValueError(
                "histogram dump uses a different bucket base "
                f"({d.get('buckets_per_octave')} buckets/octave, this build "
                f"uses {BUCKETS_PER_OCTAVE}) — buckets are not mergeable"
            )
        h = cls()
        h.counts = {int(k): int(v) for k, v in (d.get("buckets") or {}).items()}
        h.zero = int(d.get("zero", 0))
        h.n = int(d.get("n", 0))
        h.sum = float(d.get("sum", 0.0))
        h.max = float(d.get("max", 0.0))
        return h


def _ms(seconds: Optional[float]) -> Optional[float]:
    return None if seconds is None else round(seconds * 1e3, 3)


class HistogramSet:
    """Per-phase histograms keyed by span name, plus the slow-span trigger.

    ``on_slow(name, seconds, p50_seconds)`` fires when an observation
    exceeds ``slow_factor × running p50`` after ``slow_warmup`` observations
    of that phase — the flight-recorder hook. ``slow_min_s`` is an absolute
    floor: sub-millisecond phases jitter 10x on GC pauses alone, and a
    "3 ms anomaly" is never actionable, so only observations above the floor
    can trigger. The p50 is cached and refreshed every few records, so the
    hot-path cost of an observation stays one dict lookup + one ``log2``.
    """

    #: records between running-p50 refreshes (per phase)
    _P50_REFRESH = 32

    def __init__(
        self,
        slow_factor: float = 0.0,
        slow_warmup: int = 64,
        slow_min_s: float = 0.0,
        on_slow: Optional[Callable[[str, float, float], None]] = None,
    ):
        self.slow_factor = float(slow_factor)
        self.slow_warmup = int(slow_warmup)
        self.slow_min_s = float(slow_min_s)
        self.on_slow = on_slow
        self._lock = threading.Lock()
        self._hists: Dict[str, StreamingHist] = {}
        self._p50_cache: Dict[str, tuple] = {}  # name -> (p50, refresh_at_n)

    def get(self, name: str) -> StreamingHist:
        h = self._hists.get(name)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(name, StreamingHist())
        return h

    def observe(self, name: str, seconds: float) -> None:
        h = self.get(name)
        h.record(seconds)
        if self.on_slow is None or self.slow_factor <= 0 or seconds < self.slow_min_s:
            return
        n = h.n
        if n <= self.slow_warmup:
            # the p50 is only trustworthy once `slow_warmup` observations
            # precede the candidate — cold-start outliers are expected
            return
        p50, refresh_at = self._p50_cache.get(name, (None, 0))
        if p50 is None or n >= refresh_at:
            p50 = h.quantile(0.50)
            self._p50_cache[name] = (p50, n + self._P50_REFRESH)
        if p50 and seconds > self.slow_factor * p50:
            # refresh the cached p50 eagerly so a genuine regime shift (a
            # phase that legitimately got slower) re-arms at the new median
            # instead of re-firing forever
            self._p50_cache[name] = (h.quantile(0.50), n + self._P50_REFRESH)
            try:
                self.on_slow(name, seconds, p50)
            except Exception:
                # the hook runs inside span.__exit__ on the train path: a
                # telemetry bug must never take the run down
                pass

    def percentiles(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            names = sorted(self._hists)
        return {name: self._hists[name].percentiles() for name in names}

    # -- merge / serialization ----------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            names = sorted(self._hists)
        return {name: self._hists[name].to_dict() for name in names}

    def merge_dict(self, dumped: Dict[str, Any]) -> None:
        """Merge a :meth:`to_dict` dump (another rank/role) into this set."""
        for name, d in (dumped or {}).items():
            self.get(name).merge(StreamingHist.from_dict(d))

    @classmethod
    def merge_all(cls, dumps: Iterable[Dict[str, Any]]) -> "HistogramSet":
        out = cls()
        for d in dumps:
            out.merge_dict(d)
        return out
