"""Declarative SLO engine: rolling windows, multi-window burn-rate alerts.

Objectives come from ``configs/serve/default.yaml`` (``serve.slo.*``) and are
all expressed as **good/bad event streams** against an error budget — the
multiwindow burn-rate method of the SRE Workbook (Beyer et al., 2018):

- ``act_latency_p99_ms`` — "99% of requests answer within X ms"; a request
  slower than X is a bad event, the budget is 1%.
- ``availability`` — ``1 - failed/total``; a failed request is a bad event,
  the budget is ``1 - target``. Client-cancelled tickets are *excluded* from
  the denominator: the server never answered them, so they neither spend nor
  earn budget (asserted in tests/test_obs/test_slo.py).
- ``swap_staleness_s`` — a published policy version must be serving within
  X seconds. Sampled as a gauge each evaluation tick; a stale sample is a
  bad event against a near-zero budget, so a single violation burns hot and
  pages immediately (a staleness bound is a hard bound).

``burn_rate = (bad / (good + bad) over the window) / budget`` — burn 1.0
spends the budget exactly at the sustainable rate. Each objective carries a
**fast/slow alert pair**: the fast alert (short window, high threshold)
catches cliffs in seconds, the slow alert (long window, low threshold)
catches slow leaks. Alerts fire above their threshold and clear only below
``clear_ratio x threshold`` (hysteresis — a burn hovering at the threshold
must not flap). Every transition lands as one line in ``alerts.jsonl`` and
fires the ``on_alert`` hook (the gateway points it at the flight recorder,
``reason=slo_burn``).

The engine is deliberately free of serving imports and takes an injectable
clock, so tests drive hand-computed windows without sleeping; the live
wiring (evaluation thread, staleness probe, request feed) lives in
:mod:`sheeprl_tpu.serve.ops`.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["Objective", "SloEngine", "slo_settings"]

#: engine defaults — mirrored (and overridable) in configs/serve/default.yaml
_DEFAULTS: Dict[str, Any] = {
    "enabled": False,
    "window_s": 60.0,        # slow burn-rate window
    "fast_window_s": 5.0,    # fast burn-rate window
    "slow_burn": 6.0,        # slow-alert threshold (x budget rate)
    "fast_burn": 14.4,       # fast-alert threshold (x budget rate)
    "clear_ratio": 0.5,      # hysteresis: clear below clear_ratio x threshold
    "eval_interval_s": 1.0,  # evaluation-tick cadence (serve/ops.py thread)
    "objectives": {
        "act_latency_p99_ms": 250.0,
        "availability": 0.999,
        "swap_staleness_s": 30.0,
    },
}


def slo_settings(cfg: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """The ``serve.slo`` block merged over the engine defaults."""
    out = {k: (dict(v) if isinstance(v, dict) else v) for k, v in _DEFAULTS.items()}
    for key, val in dict(cfg or {}).items():
        if key == "objectives" and isinstance(val, dict):
            out["objectives"].update({k: v for k, v in val.items() if v is not None})
        elif val is not None:
            out[key] = val
    return out


class _Buckets:
    """Good/bad event counts in 1-second time buckets over a bounded horizon."""

    def __init__(self, horizon_s: float, bucket_s: float = 1.0):
        self.bucket_s = float(bucket_s)
        self._maxlen = max(2, int(horizon_s / self.bucket_s) + 2)
        self._buckets: deque = deque(maxlen=self._maxlen)  # (bucket_idx, good, bad)
        self.total_good = 0
        self.total_bad = 0

    def add(self, t: float, good: int = 0, bad: int = 0) -> None:
        idx = int(t / self.bucket_s)
        if self._buckets and self._buckets[-1][0] == idx:
            _, g, b = self._buckets[-1]
            self._buckets[-1] = (idx, g + good, b + bad)
        else:
            self._buckets.append((idx, good, bad))
        self.total_good += good
        self.total_bad += bad

    def window(self, t: float, window_s: float) -> Tuple[int, int]:
        """(good, bad) counted over the trailing ``window_s`` ending at t."""
        lo = int((t - window_s) / self.bucket_s)
        hi = int(t / self.bucket_s)
        good = bad = 0
        for idx, g, b in self._buckets:
            if lo < idx <= hi:
                good += g
                bad += b
        return good, bad


class _BurnAlert:
    """One burn-rate alert with fire/clear hysteresis."""

    def __init__(self, name: str, window_s: float, threshold: float, clear_ratio: float):
        self.name = name
        self.window_s = float(window_s)
        self.threshold = float(threshold)
        self.clear_below = float(clear_ratio) * self.threshold
        self.active = False
        self.fired = 0

    def update(self, burn: float) -> Optional[str]:
        """"fire" / "clear" on a state transition, else None."""
        if not self.active and burn > self.threshold:
            self.active = True
            self.fired += 1
            return "fire"
        if self.active and burn < self.clear_below:
            self.active = False
            return "clear"
        return None


class Objective:
    """One SLO: an error budget plus its fast/slow burn-rate alert pair."""

    def __init__(self, name: str, target: float, budget: float, settings: Dict[str, Any]):
        self.name = name
        self.target = float(target)
        #: allowed bad-event fraction; floored so a zero-budget (hard-bound)
        #: objective burns ~infinitely hot on its first bad event instead of
        #: dividing by zero
        self.budget = max(float(budget), 1e-9)
        self.events = _Buckets(horizon_s=float(settings["window_s"]))
        self.fast = _BurnAlert(
            "fast_burn", settings["fast_window_s"], settings["fast_burn"], settings["clear_ratio"]
        )
        self.slow = _BurnAlert(
            "slow_burn", settings["window_s"], settings["slow_burn"], settings["clear_ratio"]
        )

    def burn(self, t: float, window_s: float) -> Tuple[float, int, int]:
        good, bad = self.events.window(t, window_s)
        total = good + bad
        if total == 0:
            return 0.0, 0, 0
        return (bad / total) / self.budget, good, bad

    def verdict(self) -> str:
        """Cumulative whole-run compliance: PASS iff the overall bad-event
        fraction stayed inside the budget."""
        total = self.events.total_good + self.events.total_bad
        if total == 0:
            return "PASS"
        return "PASS" if (self.events.total_bad / total) <= self.budget else "FAIL"


class SloEngine:
    """The declarative engine: feed events, call :meth:`evaluate` on a tick."""

    def __init__(
        self,
        cfg: Optional[Dict[str, Any]] = None,
        alerts_path: Optional[str] = None,
        on_alert: Optional[Callable[[Dict[str, Any]], Any]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.settings = slo_settings(cfg)
        self._clock = clock
        self._on_alert = on_alert
        self._lock = threading.Lock()
        self.alerts_path = alerts_path
        self._alerts_file = None
        if alerts_path:
            os.makedirs(os.path.dirname(os.path.abspath(alerts_path)) or ".", exist_ok=True)
            self._alerts_file = open(alerts_path, "a")
        obj = self.settings["objectives"]
        self.objectives: Dict[str, Objective] = {}
        lat_ms = obj.get("act_latency_p99_ms")
        if lat_ms is not None:
            self.latency_bound_s = float(lat_ms) / 1e3
            self.objectives["act_latency_p99"] = Objective(
                "act_latency_p99", float(lat_ms), 0.01, self.settings
            )
        else:
            self.latency_bound_s = None
        avail = obj.get("availability")
        if avail is not None:
            self.objectives["availability"] = Objective(
                "availability", float(avail), 1.0 - float(avail), self.settings
            )
        stale_s = obj.get("swap_staleness_s")
        if stale_s is not None:
            self.staleness_bound_s = float(stale_s)
            self.objectives["swap_staleness"] = Objective(
                "swap_staleness", float(stale_s), 0.0, self.settings
            )
        else:
            self.staleness_bound_s = None
        self.cancelled = 0
        self.alert_log: List[Dict[str, Any]] = []

    # -- event feeds --------------------------------------------------------

    def record_request(
        self,
        latency_s: Optional[float],
        failed: bool = False,
        cancelled: bool = False,
        t: Optional[float] = None,
    ) -> None:
        """One retired act() ticket. Cancelled tickets only bump a gauge —
        the server never answered, so availability ignores them entirely."""
        t = self._clock() if t is None else t
        with self._lock:
            if cancelled:
                self.cancelled += 1
                return
            avail = self.objectives.get("availability")
            if avail is not None:
                avail.events.add(t, good=0 if failed else 1, bad=1 if failed else 0)
            lat = self.objectives.get("act_latency_p99")
            if lat is not None and not failed and latency_s is not None:
                slow = latency_s > self.latency_bound_s
                lat.events.add(t, good=0 if slow else 1, bad=1 if slow else 0)

    def record_staleness(self, staleness_s: float, t: Optional[float] = None) -> None:
        """One sampled swap-staleness gauge reading (seconds a newer
        published policy has been waiting beyond the serving version)."""
        obj = self.objectives.get("swap_staleness")
        if obj is None:
            return
        t = self._clock() if t is None else t
        with self._lock:
            stale = staleness_s > self.staleness_bound_s
            obj.events.add(t, good=0 if stale else 1, bad=1 if stale else 0)

    # -- evaluation ---------------------------------------------------------

    def evaluate(self, t: Optional[float] = None) -> List[Dict[str, Any]]:
        """One evaluation tick: update every alert pair, log transitions.

        Returns the transition records ("fire"/"clear") produced this tick.
        """
        t = self._clock() if t is None else t
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            for obj in self.objectives.values():
                for alert in (obj.fast, obj.slow):
                    burn, good, bad = obj.burn(t, alert.window_s)
                    event = alert.update(burn)
                    if event is None:
                        continue
                    transitions.append(
                        {
                            "ts_unix": round(time.time(), 3),
                            "objective": obj.name,
                            "alert": alert.name,
                            "event": event,
                            "burn_rate": round(burn, 3),
                            "threshold": alert.threshold,
                            "window_s": alert.window_s,
                            "budget": obj.budget,
                            "good": good,
                            "bad": bad,
                        }
                    )
            for rec in transitions:
                self.alert_log.append(rec)
                if self._alerts_file is not None and not self._alerts_file.closed:
                    self._alerts_file.write(json.dumps(rec) + "\n")
            if transitions and self._alerts_file is not None and not self._alerts_file.closed:
                self._alerts_file.flush()
        if self._on_alert is not None:
            for rec in transitions:
                if rec["event"] == "fire":
                    try:
                        self._on_alert(rec)
                    except Exception:
                        pass  # an alerting sink must never take serving down
        return transitions

    # -- reporting ----------------------------------------------------------

    def status(self, t: Optional[float] = None) -> Dict[str, Any]:
        """Per-objective burn rates, alert states, and cumulative verdicts."""
        t = self._clock() if t is None else t
        with self._lock:
            out: Dict[str, Any] = {
                "enabled": bool(self.settings.get("enabled")),
                "cancelled_tickets": self.cancelled,
                "alerts_fired": sum(
                    o.fast.fired + o.slow.fired for o in self.objectives.values()
                ),
                "objectives": {},
            }
            for obj in self.objectives.values():
                burn_fast, _, _ = obj.burn(t, obj.fast.window_s)
                burn_slow, _, _ = obj.burn(t, obj.slow.window_s)
                out["objectives"][obj.name] = {
                    "target": obj.target,
                    "budget": obj.budget,
                    "good": obj.events.total_good,
                    "bad": obj.events.total_bad,
                    "burn_fast": round(burn_fast, 3),
                    "burn_slow": round(burn_slow, 3),
                    "fast_active": obj.fast.active,
                    "slow_active": obj.slow.active,
                    "fired": obj.fast.fired + obj.slow.fired,
                    "verdict": obj.verdict(),
                }
            return out

    def verdicts(self) -> Dict[str, str]:
        with self._lock:
            return {name: obj.verdict() for name, obj in self.objectives.items()}

    def close(self) -> None:
        with self._lock:
            if self._alerts_file is not None and not self._alerts_file.closed:
                self._alerts_file.close()
