"""The live observability plane: streaming run snapshots, an optional
Prometheus endpoint, and the anomaly-triggered flight recorder.

PR-1 telemetry materializes one ``telemetry.json`` when the run *ends* — a
multi-hour TPU run is a black box until then. This module exports the run's
health while it is happening:

- :class:`LiveExporter` atomically rewrites ``<log_dir>/telemetry/live.json``
  every ``metric.telemetry.live_interval_s`` seconds: every counter the final
  summary has, plus rolling-window ``sps`` / ``sps_train`` /
  ``bytes_staged_h2d_per_s`` rates, per-phase ``p50/p95/p99``, watchdog beat
  ages, and peak HBM. ``tail -f`` it, or point anything that can read a JSON
  file at it.
- :class:`PromServer` (``metric.telemetry.serve_port``, disabled by default)
  serves the same snapshot as Prometheus text on ``/metrics`` (and the raw
  JSON on ``/``) from a stdlib ``http.server`` daemon thread — long runs can
  be scraped without touching the filesystem.
- :class:`FlightRecorder` keeps a bounded in-memory ring of the most recent
  trace events and, when a trigger fires — a span running ``k×`` over its
  running p50 after warmup, a post-warmup recompile, a watchdog stall, a
  non-finite loss — dumps the ring plus a counter snapshot to
  ``telemetry/flight_<reason>_<step>.json`` and optionally opens a short
  on-demand ``jax.profiler`` capture window (the capture logic that used to
  be stranded in ``tools/profile_step.py``). The evidence is captured at the
  moment of the anomaly, not reconstructed from a counter total afterwards.

Everything here is owned by :class:`~sheeprl_tpu.obs.telemetry.Telemetry`;
none of it exists (no threads, no sockets, no ring allocation) when
``metric.telemetry`` is off.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, Optional

__all__ = [
    "FlightRecorder",
    "LiveExporter",
    "PromServer",
    "profiler_capture",
    "prometheus_text",
]


def atomic_write_json(path: str, payload: Dict[str, Any]) -> None:
    """Write ``payload`` to ``path`` via a same-directory tmp + ``os.replace``
    so a concurrent reader never sees a torn file."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


# -- on-demand profiler capture ----------------------------------------------


@contextmanager
def profiler_capture(out_dir: str):
    """An XLA/TensorBoard profile of the enclosed block (``jax.profiler``
    start/stop around the caller's work). Shared by ``tools/profile_step.py``
    and the flight recorder's capture window."""
    import jax

    jax.profiler.start_trace(os.path.abspath(out_dir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# -- rolling snapshots --------------------------------------------------------


class LiveExporter:
    """Periodic atomic writer of the live run snapshot.

    ``snapshot_fn`` returns the full summary dict (the telemetry owns what
    goes in it); the exporter layers the rolling-window rates and a
    liveness header on top, then writes atomically. One snapshot is written
    immediately at start (so even a run shorter than one interval leaves a
    ``live.json``) and one final snapshot at stop.

    Rolling rates are computed over ``window_s`` of samples. The step totals
    advance at the algorithms' log boundaries (``metric.log_every``), so the
    rolling ``sps`` granularity is the log cadence; the byte/transfer
    counters advance continuously.
    """

    def __init__(
        self,
        snapshot_fn: Callable[[], Dict[str, Any]],
        path: str,
        interval_s: float = 30.0,
        window_s: float = 60.0,
    ):
        self.snapshot_fn = snapshot_fn
        self.path = path
        self.interval_s = float(interval_s)
        self.window_s = float(window_s)
        self.writes = 0
        self._samples: collections.deque = collections.deque()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._latest: Optional[Dict[str, Any]] = None
        self._latest_t = 0.0

    # -- snapshot assembly ---------------------------------------------------

    def _rolling(self, now: float, snap: Dict[str, Any]) -> Dict[str, Any]:
        self._samples.append(
            (
                now,
                snap.get("policy_steps") or 0,
                snap.get("train_steps") or 0,
                snap.get("bytes_staged_h2d") or 0,
            )
        )
        while len(self._samples) > 2 and now - self._samples[0][0] > self.window_s:
            self._samples.popleft()
        t0, p0, tr0, b0 = self._samples[0]
        dt = now - t0
        if dt <= 0 or len(self._samples) < 2:
            return {"window_s": None, "sps": None, "sps_train": None, "bytes_staged_h2d_per_s": None}
        _, p1, tr1, b1 = self._samples[-1]
        return {
            "window_s": round(dt, 1),
            "sps": round((p1 - p0) / dt, 3),
            "sps_train": round((tr1 - tr0) / dt, 3),
            "bytes_staged_h2d_per_s": round((b1 - b0) / dt, 1),
        }

    def snapshot(self) -> Dict[str, Any]:
        """Assemble (and remember) one live snapshot."""
        snap = self.snapshot_fn()
        snap["ts_unix"] = round(time.time(), 3)
        with self._lock:  # scrape threads may assemble too — samples shared
            now = time.monotonic()
            snap["rolling"] = self._rolling(now, snap)
            self._latest = snap
            self._latest_t = now
        return snap

    def latest(self) -> Optional[Dict[str, Any]]:
        """The most recent snapshot (the Prometheus endpoint reads this)."""
        with self._lock:
            return self._latest

    def latest_or_fresh(self) -> Dict[str, Any]:
        """The cached snapshot while the exporter thread keeps it current;
        a freshly computed one in serve-only mode (``interval_s=0`` — no
        thread refreshes the cache, so serving it would freeze the endpoint
        at the first scrape forever). A small staleness cap bounds the
        recompute rate so a scrape storm still cannot add load."""
        with self._lock:
            latest, latest_t = self._latest, self._latest_t
        if self._thread is not None and latest is not None:
            return latest
        if latest is not None and time.monotonic() - latest_t < 1.0:
            return latest
        return self.snapshot()

    def write_once(self) -> Dict[str, Any]:
        snap = self.snapshot()
        try:
            atomic_write_json(self.path, snap)
            self.writes += 1
        except OSError:
            pass  # a full/read-only disk must not take the run down
        return snap

    # -- lifecycle -----------------------------------------------------------

    def _run(self) -> None:
        self.write_once()
        while not self._stop.wait(self.interval_s):
            self.write_once()

    def start(self) -> None:
        if self.interval_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="obs-live-exporter", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
            self.write_once()  # final state visible after the run ends


# -- Prometheus text endpoint -------------------------------------------------


def _prom_name(key: str) -> str:
    out = []
    for ch in key:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    name = "".join(out)
    return name if not name or not name[0].isdigit() else f"_{name}"


def prometheus_text(snap: Dict[str, Any], prefix: str = "sheeprl") -> str:
    """Render a live snapshot as Prometheus exposition text (gauges).

    Scalars become ``<prefix>_<key>``; the per-phase percentile map becomes
    ``<prefix>_phase_duration_ms{phase="...",quantile="..."}`` plus a
    ``.._count`` series; rolling rates ``<prefix>_rolling_<key>``. The
    distributed sections (obs/dist) label instead of flattening: per-kind
    collectives as ``<prefix>_comms_*{kind="..."}``, staleness percentiles
    as ``<prefix>_sample_age_seconds{quantile="..."}`` /
    ``<prefix>_policy_lag_versions{quantile="..."}``, queue gauges as
    ``<prefix>_queue_depth{queue="..."}``, and every merged source
    process's numeric counters as ``<prefix>_<key>{source="player0"}``.
    The learning-health plane (obs/learn) exports its per-probe baselines —
    including the per-module grad norms — as
    ``<prefix>_learn_probe{probe="learn/grad_norm/actor",stat="p95"}``
    (the headline ``learn_warnings`` / ``learn_criticals`` /
    ``grad_norm_p95`` / ``update_ratio_p50`` ride the flat-scalar path).
    """
    lines = []

    def emit(name: str, value, labels: str = "") -> None:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            return
        lines.append(f"{prefix}_{name}{labels} {float(value):g}")

    skip = (
        "phase_percentiles",
        "rolling",
        "watchdog_beat_age_s",
        "comms",
        "staleness",
        "sources",
        "learn",
        "serve_versions",
        "slo",
        "replay_shard_fill",
    )
    for key, value in sorted(snap.items()):
        if key in skip:
            continue
        emit(_prom_name(key), value)
    for key, value in (snap.get("rolling") or {}).items():
        emit(f"rolling_{_prom_name(key)}", value)
    for role, info in (snap.get("watchdog_beat_age_s") or {}).items():
        age = info.get("age_s") if isinstance(info, dict) else info
        emit("watchdog_beat_age_seconds", age, '{role="%s"}' % role)
    for phase, pct in (snap.get("phase_percentiles") or {}).items():
        emit("phase_duration_count", pct.get("count"), '{phase="%s"}' % phase)
        for q_key, q in (("p50_ms", "0.5"), ("p95_ms", "0.95"), ("p99_ms", "0.99")):
            emit(
                "phase_duration_ms",
                pct.get(q_key),
                '{phase="%s",quantile="%s"}' % (phase, q),
            )
    for kind, rec in sorted((snap.get("comms") or {}).items()):
        lbl = '{kind="%s"}' % kind
        emit("comms_kind_ops", rec.get("ops"), lbl)
        emit("comms_kind_bytes", rec.get("bytes"), lbl)
        emit("comms_kind_ms", rec.get("ms"), lbl)
        emit("comms_achieved_gbps", rec.get("last_gbps"), lbl)
    stale = snap.get("staleness") or {}
    for section, series, unit in (
        ("sample_age_s", "sample_age_seconds", "s"),
        ("policy_lag_versions", "policy_lag_versions", "v"),
    ):
        pct = stale.get(section) or {}
        emit(f"{series}_count", pct.get("count"))
        for q_key, q in ((f"p50_{unit}", "0.5"), (f"p95_{unit}", "0.95"), (f"p99_{unit}", "0.99")):
            emit(series, pct.get(q_key), '{quantile="%s"}' % q)
    for queue, gauge in sorted((stale.get("queue_depth") or {}).items()):
        emit("queue_depth", gauge.get("last"), '{queue="%s"}' % queue)
        emit("queue_depth_max", gauge.get("max"), '{queue="%s"}' % queue)
    # sharded replay plane (sheeprl_tpu/replay): fill fraction per host shard
    for shard, fill in sorted((snap.get("replay_shard_fill") or {}).items()):
        emit("replay_shard_fill", fill, '{shard="%s"}' % shard)
    lrn = snap.get("learn") or {}
    emit("learn_bursts_observed", lrn.get("bursts_observed"))
    for probe, rec in sorted((lrn.get("probes") or {}).items()):
        emit("learn_probe_count", rec.get("n"), '{probe="%s"}' % probe)
        for stat in ("last", "p50", "p95", "max"):
            emit(
                "learn_probe",
                rec.get(stat),
                '{probe="%s",stat="%s"}' % (probe, stat),
            )
    for source, src_snap in sorted((snap.get("sources") or {}).items()):
        if not isinstance(src_snap, dict):
            continue
        lbl = '{source="%s"}' % source
        for key, value in sorted(src_snap.items()):
            emit(_prom_name(key), value, lbl)
    # serving-tier sections (serve/ops.py snapshots): per-version request /
    # latency breakdown and the SLO engine's burn rates + alert states
    for ver, rec in sorted((snap.get("serve_versions") or {}).items()):
        lbl = '{version="%s"}' % ver
        emit("serve_version_requests", rec.get("requests"), lbl)
        for q_key, q in (("p50_ms", "0.5"), ("p95_ms", "0.95"), ("p99_ms", "0.99")):
            emit(
                "serve_version_latency_ms",
                rec.get(q_key),
                '{version="%s",quantile="%s"}' % (ver, q),
            )
    slo = snap.get("slo") or {}
    emit("slo_cancelled_tickets", slo.get("cancelled_tickets"))
    emit("slo_alerts_fired", slo.get("alerts_fired"))
    for name, rec in sorted((slo.get("objectives") or {}).items()):
        lbl = '{objective="%s"}' % name
        emit("slo_burn_rate_fast", rec.get("burn_fast"), lbl)
        emit("slo_burn_rate_slow", rec.get("burn_slow"), lbl)
        emit("slo_alert_active", int(bool(rec.get("fast_active"))), '{objective="%s",alert="fast_burn"}' % name)
        emit("slo_alert_active", int(bool(rec.get("slow_active"))), '{objective="%s",alert="slow_burn"}' % name)
        emit("slo_objective_ok", int(rec.get("verdict") == "PASS"), lbl)
    return "\n".join(lines) + "\n"


class PromServer:
    """A stdlib HTTP daemon serving ``/metrics`` (Prometheus text) and ``/``
    (the raw live JSON) from the exporter's latest snapshot.

    ``port=0`` binds an ephemeral port (tests); the bound port is ``.port``.
    The server never computes a snapshot itself — a scrape returns the
    exporter's most recent one, so a scrape storm cannot add load to the run.
    """

    def __init__(self, exporter: LiveExporter, port: int, host: str = ""):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                snap = outer.exporter.latest_or_fresh()
                if self.path.startswith("/metrics"):
                    body = prometheus_text(snap).encode()
                    ctype = "text/plain; version=0.0.4"
                else:
                    body = (json.dumps(snap, indent=2, sort_keys=True) + "\n").encode()
                    ctype = "application/json"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes must not spam the run log
                pass

        self.exporter = exporter
        self._server = ThreadingHTTPServer((host, int(port)), Handler)
        self._server.daemon_threads = True
        self.port = int(self._server.server_address[1])
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="obs-prom-endpoint",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)
        self._thread = None


# -- flight recorder ----------------------------------------------------------


class FlightRecorder:
    """Bounded ring of recent trace events, dumped at the moment of an
    anomaly.

    :meth:`record` is the ring feed — the :class:`~sheeprl_tpu.obs.spans.
    TraceWriter` calls it for every event it emits (the ring works even with
    the trace *file* disabled, e.g. bench runs). :meth:`trigger` dumps
    ``telemetry/flight_<reason>_<step>.json``: the ring, a counter snapshot,
    the per-phase percentiles, and the trigger detail. Dumps are
    rate-limited (``min_interval_s`` between dumps, ``max_dumps`` per run) so
    a pathological run leaves a handful of evidence files, not a disk full.

    ``profiler_capture_s > 0`` additionally opens one ``jax.profiler``
    capture window per trigger episode on a daemon thread, landing an XLA
    trace of the anomalous steady state next to the dump.
    """

    def __init__(
        self,
        capacity: int = 2048,
        min_interval_s: float = 30.0,
        max_dumps: int = 8,
        profiler_capture_s: float = 0.0,
        out_dir: Optional[str] = None,
        step_source: Optional[Callable[[], int]] = None,
        context_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        tag: str = "",
    ):
        self.ring: collections.deque = collections.deque(maxlen=int(capacity))
        self.min_interval_s = float(min_interval_s)
        self.max_dumps = int(max_dumps)
        self.profiler_capture_s = float(profiler_capture_s)
        self.out_dir = out_dir
        self.step_source = step_source
        self.context_fn = context_fn
        self.tag = tag  # per-rank suffix so shared run dirs don't collide
        self.dumps = 0
        self.suppressed = 0
        self.dump_files: list = []
        self._lock = threading.Lock()
        self._last_dump_t = 0.0
        self._suppressed_since_dump = 0
        self._capturing = False

    def attach_dir(self, out_dir: str, tag: str = "") -> None:
        self.out_dir = out_dir
        self.tag = tag

    def record(self, event: Dict[str, Any]) -> None:
        """Append one trace event (deque.append is atomic — no lock)."""
        self.ring.append(event)

    # -- triggers ------------------------------------------------------------

    def trigger(self, reason: str, detail: Dict[str, Any]) -> Optional[str]:
        """Fire the recorder; returns the dump path (None when rate-limited
        or no run dir is attached yet)."""
        now = time.monotonic()
        with self._lock:
            if (
                self.out_dir is None
                or self.dumps >= self.max_dumps
                or (self._last_dump_t and now - self._last_dump_t < self.min_interval_s)
            ):
                self.suppressed += 1
                self._suppressed_since_dump += 1
                return None
            # _last_dump_t advances even if the write below fails: a full
            # disk must not turn every trigger into a write attempt
            self._last_dump_t = now
            self.dumps += 1
            suppressed_before = self._suppressed_since_dump
            self._suppressed_since_dump = 0
        # other threads keep appending trace events while we snapshot the
        # ring (record() is lock-free by design); deque iteration raises
        # RuntimeError on concurrent mutation, so retry a few times
        events: list = []
        for _ in range(8):
            try:
                events = list(self.ring)
                break
            except RuntimeError:
                continue
        step = 0
        if self.step_source is not None:
            try:
                step = int(self.step_source())
            except Exception:
                pass
        stem = f"flight_{reason}_{step}{self.tag}"
        path = os.path.join(self.out_dir, f"{stem}.json")
        k = 1
        while os.path.exists(path):
            path = os.path.join(self.out_dir, f"{stem}_{k}.json")
            k += 1
        payload: Dict[str, Any] = {
            "reason": reason,
            "detail": detail,
            "step": step,
            "ts_unix": round(time.time(), 3),
            # triggers rate-limited away since the previous dump — an
            # operator reading one dump of a storm sees the storm's size
            "suppressed_before": suppressed_before,
            "events": events,
        }
        if self.context_fn is not None:
            try:
                payload["context"] = self.context_fn()
            except Exception:
                pass
        try:
            atomic_write_json(path, payload)
        except OSError:
            with self._lock:  # nothing landed: give the budget back
                self.dumps -= 1
            return None
        self.dump_files.append(path)
        from sheeprl_tpu.obs.spans import get_tracer

        tracer = get_tracer()
        if tracer is not None:
            tracer.instant(f"flight_{reason}", cat="flight", args=detail)
        if self.profiler_capture_s > 0:
            self._capture_window(f"{path[:-5]}_xla")
        return path

    def _capture_window(self, out_dir: str) -> None:
        # the StepProfiler (obs/prof/capture.py) may hold the process-wide
        # jax.profiler slot — arbitrate through the shared guard so two
        # capture paths never race a start_trace into a tracing runtime
        from sheeprl_tpu.obs.prof.capture import end_capture, parse_and_fold, try_begin_capture

        with self._lock:
            if self._capturing:
                return
            self._capturing = True
        if not try_begin_capture():
            with self._lock:
                self._capturing = False
            return

        def _run():
            captured = False
            try:
                with profiler_capture(out_dir):
                    time.sleep(self.profiler_capture_s)
                captured = True
            except Exception:
                pass  # a failed capture must never take the run down
            finally:
                end_capture()
                with self._lock:
                    self._capturing = False
            if captured:
                # auto-parse the anomaly trace: the roofline summary lands
                # next to the dump instead of waiting for a hand-run parse
                from sheeprl_tpu.obs.telemetry import get_telemetry

                record = parse_and_fold(out_dir, get_telemetry())
                if record is not None:
                    try:
                        atomic_write_json(f"{out_dir}_summary.json", record)
                    except OSError:
                        pass

        threading.Thread(
            target=_run, name="obs-flight-capture", daemon=True
        ).start()
