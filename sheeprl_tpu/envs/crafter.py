"""Crafter adapter (reference ``sheeprl/envs/crafter.py`` :17-65):
``crafter_reward`` / ``crafter_nonreward`` variants behind the gymnasium API
with a single ``rgb`` dict key. Import-gated on ``crafter``."""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_CRAFTER_AVAILABLE

if not _IS_CRAFTER_AVAILABLE:
    raise ModuleNotFoundError("crafter is required: pip install crafter")

from typing import Any, Dict, Optional, Tuple, Union

import crafter
import gymnasium as gym
import numpy as np
from gymnasium import spaces


class CrafterWrapper(gym.Wrapper):
    def __init__(self, id: str, screen_size: Union[int, Tuple[int, int]], seed: Optional[int] = None):
        if id not in ("crafter_reward", "crafter_nonreward"):
            raise ValueError(f"Unknown crafter id: {id}")
        if isinstance(screen_size, int):
            screen_size = (screen_size, screen_size)
        env = crafter.Env(size=screen_size, seed=seed, reward=(id == "crafter_reward"))
        super().__init__(env)
        inner = self.env.observation_space
        self.observation_space = spaces.Dict(
            {"rgb": spaces.Box(inner.low, inner.high, inner.shape, inner.dtype)}
        )
        self.action_space = spaces.Discrete(self.env.action_space.n)
        self.reward_range = self.env.reward_range or (-np.inf, np.inf)
        self.observation_space.seed(seed)
        self.action_space.seed(seed)
        self._render_mode = "rgb_array"
        self._metadata = {"render_fps": 30}

    @property
    def render_mode(self) -> Optional[str]:
        return self._render_mode

    def step(self, action: Any):
        obs, reward, done, info = self.env.step(action)
        return {"rgb": obs}, reward, done, False, info

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        obs = self.env.reset()
        return {"rgb": obs}, {}

    def render(self):
        return self.env.render()

    def close(self) -> None:
        return
