"""DeepMind Control Suite adapter.

Behavioral contract from the reference ``sheeprl/envs/dmc.py`` (DMCWrapper
:49-234, itself adapted from dmc2gym): spec→Box conversion, a normalized
``[-1, 1]`` action space rescaled to the true bounds, pixel and/or flattened
vector observations under the ``rgb``/``state`` keys, and
``discount``/``internal_state`` extras per step.

Import-gated: requires ``dm_control`` (reference imports.py probe).
"""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_DMC_AVAILABLE

if not _IS_DMC_AVAILABLE:
    raise ModuleNotFoundError(
        "dm_control is required for the DMC environments: pip install dm_control"
    )

import ctypes.util
import os
from typing import Any, Dict, Optional, Tuple


def _pick_gl_backend() -> str:
    """Offscreen GL backend for headless hosts, probed before import.

    MuJoCo hard-crashes deep inside PyOpenGL when ``MUJOCO_GL`` names a
    backend whose shared library is missing (``'NoneType' object has no
    attribute 'eglQueryString'`` on EGL-less containers), so the choice must
    be made from what the loader can actually find: EGL first (TPU VM
    images), then OSMesa (software rasterizer), else rendering is switched
    ``off`` — physics and vector observations still work; only
    ``from_pixels`` needs a renderer (guarded in :class:`DMCWrapper`)."""
    for backend, lib in (("egl", "EGL"), ("osmesa", "OSMesa")):
        if ctypes.util.find_library(lib):
            return backend
    return "off"


# Headless hosts (no DISPLAY — every TPU VM) need an offscreen GL backend
# for pixel observations. Respect an explicit user choice.
if "DISPLAY" not in os.environ:
    os.environ.setdefault("MUJOCO_GL", _pick_gl_backend())

import gymnasium as gym
import numpy as np
from dm_control import suite
from dm_env import specs
from gymnasium import spaces


def _spec_to_box(spec_list, dtype) -> spaces.Box:
    """dm_env specs → one flat gym Box (reference :17-38)."""
    lows, highs = [], []
    for s in spec_list:
        dim = int(np.prod(s.shape))
        if isinstance(s, specs.BoundedArray):
            lows.append(np.broadcast_to(s.minimum, (dim,)).astype(np.float32))
            highs.append(np.broadcast_to(s.maximum, (dim,)).astype(np.float32))
        elif isinstance(s, specs.Array):
            lows.append(np.full(dim, -np.inf, np.float32))
            highs.append(np.full(dim, np.inf, np.float32))
        else:
            raise ValueError(f"Unrecognized spec: {type(s)}")
    low = np.concatenate(lows).astype(dtype)
    high = np.concatenate(highs).astype(dtype)
    return spaces.Box(low, high, dtype=dtype)


def _flatten_obs(obs: Dict[Any, Any]) -> np.ndarray:
    pieces = [np.array([v]) if np.isscalar(v) else np.asarray(v).ravel() for v in obs.values()]
    return np.concatenate(pieces, axis=0)


class DMCWrapper(gym.Env):
    """dm_control task behind the gymnasium API (reference :49-234; a plain
    ``gym.Env`` holding the dm_env, since gymnasium 1.x ``Wrapper`` refuses
    non-gymnasium inner envs)."""

    def __init__(
        self,
        id: str,
        from_pixels: bool = False,
        from_vectors: bool = True,
        height: int = 84,
        width: int = 84,
        camera_id: int = 0,
        task_kwargs: Optional[Dict[Any, Any]] = None,
        environment_kwargs: Optional[Dict[Any, Any]] = None,
        channels_first: bool = True,
        visualize_reward: bool = False,
        seed: Optional[int] = None,
    ):
        if not (from_vectors or from_pixels):
            raise ValueError(
                "'from_vectors' and 'from_pixels' must not be both False: "
                f"got {from_vectors} and {from_pixels} respectively."
            )
        if from_pixels and os.environ.get("MUJOCO_GL", "") == "off":
            raise RuntimeError(
                "Pixel observations need an offscreen GL backend, but no EGL "
                "or OSMesa library was found on this host (MUJOCO_GL=off). "
                "Install libEGL/libOSMesa or set MUJOCO_GL explicitly; vector "
                "observations (from_vectors=True, from_pixels=False) work "
                "without a renderer."
            )
        domain_name, task_name = id.split("_", 1)
        self._from_pixels = from_pixels
        self._from_vectors = from_vectors
        self._height = height
        self._width = width
        self._camera_id = camera_id
        self._channels_first = channels_first

        # Seed the SIMULATION, not just the spaces (reference dmc.py:75-78
        # builds task_kwargs={"random": seed}): without this, dm_control
        # falls back to an OS-entropy RandomState and episode initial states
        # are irreproducible regardless of every other seed in the run.
        task_kwargs = dict(task_kwargs or {})
        if seed is not None:
            task_kwargs.setdefault("random", seed)

        env = suite.load(
            domain_name=domain_name,
            task_name=task_name,
            task_kwargs=task_kwargs,
            visualize_reward=visualize_reward,
            environment_kwargs=environment_kwargs,
        )
        self.env = env

        self._true_action_space = _spec_to_box([env.action_spec()], np.float32)
        self._norm_action_space = spaces.Box(
            low=-1.0, high=1.0, shape=self._true_action_space.shape, dtype=np.float32
        )
        reward_space = _spec_to_box([env.reward_spec()], np.float32)
        self._reward_range = (float(reward_space.low.item()), float(reward_space.high.item()))

        obs_space = {}
        if from_pixels:
            shape = (3, height, width) if channels_first else (height, width, 3)
            obs_space["rgb"] = spaces.Box(0, 255, shape, np.uint8)
        if from_vectors:
            obs_space["state"] = _spec_to_box(env.observation_spec().values(), np.float64)
        self._observation_space = spaces.Dict(obs_space)
        self._state_space = _spec_to_box(env.observation_spec().values(), np.float64)
        self.current_state = None
        self._render_mode = "rgb_array"
        self._metadata = {}
        self.seed(seed=seed)

    def __getattr__(self, name):
        if name.startswith("_") or name == "env":
            raise AttributeError(name)
        return getattr(self.env, name)

    @property
    def observation_space(self):
        return self._observation_space

    @property
    def state_space(self) -> spaces.Box:
        return self._state_space

    @property
    def action_space(self) -> spaces.Box:
        return self._norm_action_space

    @property
    def reward_range(self) -> Tuple[float, float]:
        return self._reward_range

    @property
    def render_mode(self) -> str:
        return self._render_mode

    def seed(self, seed: Optional[int] = None):
        self._true_action_space.seed(seed)
        self._norm_action_space.seed(seed)
        self._observation_space.seed(seed)

    def _get_obs(self, time_step) -> Dict[str, np.ndarray]:
        obs = {}
        if self._from_pixels:
            rgb = self.render(camera_id=self._camera_id)
            if self._channels_first:
                rgb = rgb.transpose(2, 0, 1).copy()
            obs["rgb"] = rgb
        if self._from_vectors:
            obs["state"] = _flatten_obs(time_step.observation)
        return obs

    def _denormalize_action(self, action: np.ndarray) -> np.ndarray:
        """[-1, 1] → true bounds (reference :180-188)."""
        action = np.asarray(action, np.float64)
        frac = (action - self._norm_action_space.low) / (
            self._norm_action_space.high - self._norm_action_space.low
        )
        true = frac * (
            self._true_action_space.high - self._true_action_space.low
        ) + self._true_action_space.low
        return true.astype(np.float32)

    def step(self, action):
        time_step = self.env.step(self._denormalize_action(action))
        reward = time_step.reward or 0.0
        done = time_step.last()
        obs = self._get_obs(time_step)
        self.current_state = _flatten_obs(time_step.observation)
        extra = {
            "discount": time_step.discount,
            "internal_state": self.env.physics.get_state().copy(),
        }
        return obs, reward, done, False, extra

    def reset(self, seed: Optional[int] = None, options=None):
        time_step = self.env.reset()
        self.current_state = _flatten_obs(time_step.observation)
        return self._get_obs(time_step), {}

    def render(self, camera_id: Optional[int] = None) -> np.ndarray:
        return self.env.physics.render(
            height=self._height, width=self._width, camera_id=camera_id or self._camera_id
        )

    def close(self) -> None:
        self.env.close()
