"""Generic gymnasium wrappers.

TPU-native re-implementation of the reference wrapper set
(``sheeprl/envs/wrappers.py``: MaskVelocityWrapper :11, ActionRepeat :46,
RestartOnException :72, dilated FrameStack :124, RewardAsObservationWrapper
:183, GrayscaleRenderWrapper :242). All of these run on the CPU host side of
the pipeline — they never see a jax array — so the design goal here is low
Python overhead per step (the host loop competes with the TPU for wall-clock).
"""

from __future__ import annotations

import copy
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import gymnasium as gym
import numpy as np


class MaskVelocityWrapper(gym.ObservationWrapper):
    """Zero out velocity entries to make classic-control MDPs partially
    observable (reference wrappers.py:11-43)."""

    velocity_indices: Dict[str, np.ndarray] = {
        "CartPole-v0": np.array([1, 3]),
        "CartPole-v1": np.array([1, 3]),
        "MountainCar-v0": np.array([1]),
        "MountainCarContinuous-v0": np.array([1]),
        "Pendulum-v1": np.array([2]),
        "LunarLander-v2": np.array([2, 3, 5]),
        "LunarLanderContinuous-v2": np.array([2, 3, 5]),
    }

    def __init__(self, env: gym.Env):
        super().__init__(env)
        if env.unwrapped.spec is None:
            raise NotImplementedError("MaskVelocityWrapper needs a spec'd env")
        env_id = env.unwrapped.spec.id
        if env_id not in self.velocity_indices:
            raise NotImplementedError(f"Velocity masking not implemented for {env_id}")
        self.mask = np.ones(env.observation_space.shape, dtype=np.float32)
        self.mask[self.velocity_indices[env_id]] = 0.0

    def observation(self, observation: np.ndarray) -> np.ndarray:
        return observation * self.mask


class ActionRepeat(gym.Wrapper):
    """Repeat each action ``amount`` times, summing rewards; stop early on
    termination (reference wrappers.py:46-70)."""

    def __init__(self, env: gym.Env, amount: int = 1):
        super().__init__(env)
        if amount <= 0:
            raise ValueError("`amount` should be a positive integer")
        self._amount = int(amount)

    @property
    def action_repeat(self) -> int:
        return self._amount

    def step(self, action):
        total_reward = 0.0
        obs, done, truncated, info = None, False, False, {}
        for _ in range(self._amount):
            obs, reward, done, truncated, info = self.env.step(action)
            total_reward += reward
            if done or truncated:
                break
        return obs, total_reward, done, truncated, info


class RestartOnException(gym.Wrapper):
    """Fault tolerance: rebuild a crashed env from its factory and keep going.

    Reference behavior (wrappers.py:72-121): on exception during step/reset,
    rebuild via ``env_fn`` after ``wait`` seconds and return a reset
    observation with ``info["restart_on_exception"]=True``; more than
    ``maxfails`` crashes within ``window`` seconds re-raises.
    """

    def __init__(
        self,
        env_fn: Callable[[], gym.Env],
        exceptions: Union[type, Sequence[type]] = (Exception,),
        window: float = 300,
        maxfails: int = 2,
        wait: float = 20,
    ):
        if not isinstance(exceptions, (tuple, list)):
            exceptions = (exceptions,)
        self._env_fn = env_fn
        self._exceptions = tuple(exceptions)
        self._window = window
        self._maxfails = maxfails
        self._wait = wait
        self._last_fail_time = time.time()
        self._fails = 0
        super().__init__(env_fn())

    def _handle_crash(self, phase: str, e: Exception) -> Tuple[Any, Dict[str, Any]]:
        now = time.time()
        if now > self._last_fail_time + self._window:
            self._last_fail_time = now
            self._fails = 1
        else:
            self._fails += 1
        if self._fails > self._maxfails:
            raise RuntimeError(f"The env crashed too many times: {self._fails}")
        gym.logger.warn(f"{phase} - Restarting env after crash with {type(e).__name__}: {e}")
        time.sleep(self._wait)
        self.env = self._env_fn()
        obs, info = self.env.reset()
        info["restart_on_exception"] = True
        return obs, info

    def step(self, action):
        try:
            return self.env.step(action)
        except self._exceptions as e:
            obs, info = self._handle_crash("STEP", e)
            return obs, 0.0, False, False, info

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        try:
            return self.env.reset(seed=seed, options=options)
        except self._exceptions as e:
            return self._handle_crash("RESET", e)


class FrameStack(gym.Wrapper):
    """Stack the last ``num_stack`` image frames (optionally dilated) along a
    new leading axis, per cnn key (reference wrappers.py:124-180).

    With ``dilation=d`` the stacked frames are every d-th of the last
    ``num_stack*d`` raw frames.
    """

    def __init__(self, env: gym.Env, num_stack: int, cnn_keys: Sequence[str], dilation: int = 1):
        super().__init__(env)
        if num_stack <= 0:
            raise ValueError(f"Invalid value for num_stack, expected a value greater than zero, got {num_stack}")
        if not isinstance(env.observation_space, gym.spaces.Dict):
            raise RuntimeError(
                f"Expected an observation space of type gym.spaces.Dict, got: {type(env.observation_space)}"
            )
        self._num_stack = num_stack
        self._dilation = dilation
        self._cnn_keys = [k for k, v in env.observation_space.spaces.items() if cnn_keys and len(v.shape) == 3]
        if not self._cnn_keys:
            raise RuntimeError("Specify at least one valid cnn key to be stacked")
        self.observation_space = copy.deepcopy(env.observation_space)
        for k in self._cnn_keys:
            space = env.observation_space[k]
            self.observation_space[k] = gym.spaces.Box(
                np.repeat(space.low[None], num_stack, axis=0),
                np.repeat(space.high[None], num_stack, axis=0),
                (num_stack, *space.shape),
                space.dtype,
            )
        self._frames = {k: deque(maxlen=num_stack * dilation) for k in self._cnn_keys}

    def _stacked(self, key: str) -> np.ndarray:
        frames = list(self._frames[key])[self._dilation - 1 :: self._dilation]
        assert len(frames) == self._num_stack
        return np.stack(frames, axis=0)

    def step(self, action):
        obs, reward, done, truncated, info = self.env.step(action)
        for k in self._cnn_keys:
            self._frames[k].append(obs[k])
            obs[k] = self._stacked(k)
        return obs, reward, done, truncated, info

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None, **kwargs):
        obs, info = self.env.reset(seed=seed, **kwargs)
        for k in self._cnn_keys:
            self._frames[k].clear()
            self._frames[k].extend([obs[k]] * (self._num_stack * self._dilation))
            obs[k] = self._stacked(k)
        return obs, info


class RewardAsObservationWrapper(gym.Wrapper):
    """Expose the scalar reward as a ``reward`` observation key (reference
    wrappers.py:183-239). Non-dict obs spaces become ``{"obs", "reward"}``."""

    def __init__(self, env: gym.Env):
        super().__init__(env)
        reward_range = getattr(env, "reward_range", None) or (-np.inf, np.inf)
        reward_space = gym.spaces.Box(*reward_range, (1,), np.float32)
        if isinstance(env.observation_space, gym.spaces.Dict):
            self.observation_space = gym.spaces.Dict(
                {"reward": reward_space, **dict(env.observation_space.spaces)}
            )
        else:
            self.observation_space = gym.spaces.Dict(
                {"obs": env.observation_space, "reward": reward_space}
            )

    def _convert(self, obs: Any, reward: Union[float, np.ndarray]) -> Dict[str, Any]:
        reward_obs = np.asarray(reward, dtype=np.float32).reshape(-1)
        if isinstance(obs, dict):
            obs["reward"] = reward_obs
            return obs
        return {"obs": obs, "reward": reward_obs}

    def step(self, action):
        obs, reward, done, truncated, info = self.env.step(action)
        return self._convert(obs, reward), reward, done, truncated, info

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        obs, info = self.env.reset(seed=seed, options=options)
        return self._convert(obs, 0.0), info


class GrayscaleRenderWrapper(gym.Wrapper):
    """Make grayscale renders 3-channel so video encoders accept them
    (reference wrappers.py:242-253)."""

    def render(self) -> Optional[Union[np.ndarray, List[np.ndarray]]]:
        frame = super().render()
        if isinstance(frame, np.ndarray):
            if frame.ndim == 2:
                frame = frame[..., None]
            if frame.ndim == 3 and frame.shape[-1] == 1:
                frame = frame.repeat(3, axis=-1)
        return frame
