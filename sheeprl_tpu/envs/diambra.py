"""DIAMBRA Arena adapter (reference ``sheeprl/envs/diambra.py`` :23-138):
arcade fighting games with discrete / multi-discrete action spaces; every
non-Box observation key is normalized to an integer Box. Import-gated on
``diambra`` + ``diambra.arena``."""

from __future__ import annotations

import warnings

from sheeprl_tpu.utils.imports import (
    _IS_DIAMBRA_ARENA_AVAILABLE,
    _IS_DIAMBRA_AVAILABLE,
)

if not _IS_DIAMBRA_AVAILABLE or not _IS_DIAMBRA_ARENA_AVAILABLE:
    raise ModuleNotFoundError(
        "diambra and diambra-arena are required: pip install diambra diambra-arena"
    )

from typing import Any, Dict, Optional, Tuple, Union

import diambra
import diambra.arena
import gymnasium as gym
import numpy as np
from diambra.arena import EnvironmentSettings, SpaceTypes, WrappersSettings


def _resolve_space_type(name: str):
    # the config carries the reference's dotted string form
    return SpaceTypes.DISCRETE if name.rsplit(".", 1)[-1] == "DISCRETE" else SpaceTypes.MULTI_DISCRETE


class DiambraWrapper(gym.Wrapper):
    def __init__(
        self,
        id: str,
        action_space: str = "diambra.arena.SpaceTypes.DISCRETE",
        screen_size: Union[int, Tuple[int, int]] = 64,
        grayscale: bool = False,
        repeat_action: int = 1,
        rank: int = 0,
        diambra_settings: Optional[Dict[str, Any]] = None,
        diambra_wrappers: Optional[Dict[str, Any]] = None,
        render_mode: str = "rgb_array",
        log_level: int = 0,
        increase_performance: bool = True,
    ) -> None:
        if isinstance(screen_size, int):
            screen_size = (screen_size, screen_size)
        diambra_settings = dict(diambra_settings or {})
        diambra_wrappers = dict(diambra_wrappers or {})

        for disabled in ("frame_shape", "n_players"):
            if diambra_settings.pop(disabled, None) is not None:
                warnings.warn(f"The DIAMBRA {disabled} setting is disabled")
        role = diambra_settings.pop("role", None)
        self._action_type = (
            "discrete" if _resolve_space_type(action_space) == SpaceTypes.DISCRETE else "multi-discrete"
        )
        settings = EnvironmentSettings(
            **diambra_settings,
            game_id=id,
            action_space=_resolve_space_type(action_space),
            n_players=1,
            role=role,
            render_mode=render_mode,
        )
        if repeat_action > 1:
            if getattr(settings, "step_ratio", 1) > 1:
                warnings.warn(
                    f"step_ratio modified to 1 because the sticky action is active ({repeat_action})"
                )
            settings.step_ratio = 1
        for disabled in ("frame_shape", "stack_frames", "dilation", "flatten"):
            if diambra_wrappers.pop(disabled, None) is not None:
                warnings.warn(f"The DIAMBRA {disabled} wrapper is disabled")
        wrappers = WrappersSettings(
            **diambra_wrappers,
            flatten=True,
            repeat_action=repeat_action,
        )
        # resize in the engine (fast) or in the wrapper (reference :79-83)
        if increase_performance:
            settings.frame_shape = screen_size + (int(grayscale),)
        else:
            wrappers.frame_shape = screen_size + (int(grayscale),)
        env = diambra.arena.make(
            id, settings, wrappers, rank=rank, render_mode=render_mode, log_level=log_level
        )
        super().__init__(env)

        self.action_space = self.env.action_space
        obs = {}
        for k, space in self.env.observation_space.spaces.items():
            if isinstance(space, gym.spaces.Box):
                obs[k] = space
            elif isinstance(space, gym.spaces.Discrete):
                obs[k] = gym.spaces.Box(0, space.n - 1, (1,), np.int32)
            elif isinstance(space, gym.spaces.MultiDiscrete):
                obs[k] = gym.spaces.Box(
                    np.zeros_like(space.nvec), space.nvec - 1, (len(space.nvec),), np.int32
                )
            else:
                raise RuntimeError(f"Invalid observation space, got: {type(space)}")
        self.observation_space = gym.spaces.Dict(obs)
        self._render_mode = render_mode

    @property
    def render_mode(self) -> Optional[str]:
        return self._render_mode

    def __getattr__(self, name):
        return getattr(self.env, name)

    def _convert_obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        return {
            k: np.asarray(v).reshape(self.observation_space[k].shape) for k, v in obs.items()
        }

    def step(self, action: Any):
        if self._action_type == "discrete" and isinstance(action, np.ndarray):
            action = action.squeeze().item()
        obs, reward, done, truncated, infos = self.env.step(action)
        infos["env_domain"] = "DIAMBRA"
        return self._convert_obs(obs), reward, done or infos.get("env_done", False), truncated, infos

    def render(self, mode: str = "rgb_array", **kwargs):
        return self.env.render()

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        obs, infos = self.env.reset(seed=seed, options=options)
        infos["env_domain"] = "DIAMBRA"
        return self._convert_obs(obs), infos
