"""Custom MineRL Navigate task (reference
``sheeprl/envs/minerl_envs/navigate.py`` :19-95): reach a diamond block
guided by a compass; +100 on touch, optional dense per-block shaping."""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_MINERL_AVAILABLE

if not _IS_MINERL_AVAILABLE:
    raise ModuleNotFoundError("minerl is required: pip install minerl==0.4.4")

from typing import List

import minerl.herobraine.hero.handlers as handlers
from minerl.herobraine.hero.handler import Handler
from minerl.herobraine.hero.mc import MS_PER_STEP

from sheeprl_tpu.envs.minerl_envs.backend import CustomSimpleEmbodimentEnvSpec

NAVIGATE_STEPS = 6000


class CustomNavigate(CustomSimpleEmbodimentEnvSpec):
    def __init__(self, dense: bool = False, extreme: bool = False, *args, **kwargs):
        suffix = ("Extreme" if extreme else "") + ("Dense" if dense else "")
        self.dense, self.extreme = dense, extreme
        super().__init__(
            f"CustomMineRLNavigate{suffix}-v0", *args, max_episode_steps=NAVIGATE_STEPS, **kwargs
        )

    def is_from_folder(self, folder: str) -> bool:
        return folder == ("navigateextreme" if self.extreme else "navigate")

    def create_observables(self) -> List[Handler]:
        return super().create_observables() + [
            handlers.CompassObservation(angle=True, distance=False),
            handlers.FlatInventoryObservation(["dirt"]),
        ]

    def create_actionables(self) -> List[Handler]:
        return super().create_actionables() + [
            handlers.PlaceBlock(["none", "dirt"], _other="none", _default="none")
        ]

    def create_rewardables(self) -> List[Handler]:
        rewards = [
            handlers.RewardForTouchingBlockType(
                [{"type": "diamond_block", "behaviour": "onceOnly", "reward": 100.0}]
            )
        ]
        if self.dense:
            rewards.append(handlers.RewardForDistanceTraveledToCompassTarget(reward_per_block=1.0))
        return rewards

    def create_agent_start(self) -> List[Handler]:
        return super().create_agent_start() + [
            handlers.SimpleInventoryAgentStart([dict(type="compass", quantity="1")])
        ]

    def create_agent_handlers(self) -> List[Handler]:
        return [handlers.AgentQuitFromTouchingBlockType(["diamond_block"])]

    def create_server_world_generators(self) -> List[Handler]:
        if self.extreme:
            return [handlers.BiomeGenerator(biome=3, force_reset=True)]
        return [handlers.DefaultWorldGenerator(force_reset=True)]

    def create_server_quit_producers(self) -> List[Handler]:
        return [
            handlers.ServerQuitFromTimeUp(NAVIGATE_STEPS * MS_PER_STEP),
            handlers.ServerQuitWhenAnyAgentFinishes(),
        ]

    def create_server_decorators(self) -> List[Handler]:
        return [
            handlers.NavigationDecorator(
                max_randomized_radius=64,
                min_randomized_radius=64,
                block="diamond_block",
                placement="surface",
                max_radius=8,
                min_radius=0,
                max_randomized_distance=8,
                min_randomized_distance=0,
                randomize_compass_location=True,
            )
        ]

    def create_server_initial_conditions(self) -> List[Handler]:
        return [
            handlers.TimeInitialCondition(allow_passage_of_time=False, start_time=6000),
            handlers.WeatherInitialCondition("clear"),
            handlers.SpawningInitialCondition("false"),
        ]

    def get_docstring(self) -> str:
        return "Navigate to the diamond block indicated by the compass."

    def determine_success_from_rewards(self, rewards: list) -> bool:
        threshold = 100.0 + (60.0 if self.dense else 0.0)
        return sum(rewards) >= threshold
