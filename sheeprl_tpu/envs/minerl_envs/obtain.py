"""Custom MineRL Obtain tasks (reference
``sheeprl/envs/minerl_envs/obtain.py`` :24-330): survival-start item
hierarchies with staged rewards up to a diamond / iron pickaxe."""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_MINERL_AVAILABLE

if not _IS_MINERL_AVAILABLE:
    raise ModuleNotFoundError("minerl is required: pip install minerl==0.4.4")

from typing import Dict, List, Union

import minerl.herobraine.hero.handlers as handlers
from minerl.herobraine.hero.handler import Handler
from minerl.herobraine.hero.mc import MS_PER_STEP

from sheeprl_tpu.envs.minerl_envs.backend import CustomSimpleEmbodimentEnvSpec

_NONE = "none"
_OTHER = "other"

_INVENTORY_ITEMS = [
    "dirt", "coal", "torch", "log", "planks", "stick", "crafting_table",
    "wooden_axe", "wooden_pickaxe", "stone", "cobblestone", "furnace",
    "stone_axe", "stone_pickaxe", "iron_ore", "iron_ingot", "iron_axe",
    "iron_pickaxe",
]
_EQUIP_ITEMS = [
    "air", "wooden_axe", "wooden_pickaxe", "stone_axe", "stone_pickaxe",
    "iron_axe", "iron_pickaxe",
]


def _snake_to_camel(s: str) -> str:
    return "".join(p.title() for p in s.split("_"))


class CustomObtain(CustomSimpleEmbodimentEnvSpec):
    def __init__(
        self,
        target_item: str,
        dense: bool,
        reward_schedule: List[Dict[str, Union[str, int, float]]],
        *args,
        max_episode_steps: int = 6000,
        **kwargs,
    ):
        self.target_item = target_item
        self.dense = dense
        self.reward_schedule = reward_schedule
        suffix = _snake_to_camel(target_item) + ("Dense" if dense else "")
        super().__init__(
            *args,
            name=f"CustomMineRLObtain{suffix}-v0",
            max_episode_steps=max_episode_steps,
            **kwargs,
        )

    def create_observables(self) -> List[Handler]:
        return super().create_observables() + [
            handlers.FlatInventoryObservation(_INVENTORY_ITEMS),
            handlers.EquippedItemObservation(
                items=_EQUIP_ITEMS + [_OTHER], _default="air", _other=_OTHER
            ),
        ]

    def create_actionables(self):
        return super().create_actionables() + [
            handlers.PlaceBlock(
                [_NONE, "dirt", "stone", "cobblestone", "crafting_table", "furnace", "torch"],
                _other=_NONE, _default=_NONE,
            ),
            handlers.EquipAction([_NONE] + _EQUIP_ITEMS, _other=_NONE, _default=_NONE),
            handlers.CraftAction(
                [_NONE, "torch", "stick", "planks", "crafting_table"], _other=_NONE, _default=_NONE
            ),
            handlers.CraftNearbyAction(
                [_NONE, "wooden_axe", "wooden_pickaxe", "stone_axe", "stone_pickaxe",
                 "iron_axe", "iron_pickaxe", "furnace"],
                _other=_NONE, _default=_NONE,
            ),
            handlers.SmeltItemNearby([_NONE, "iron_ingot", "coal"], _other=_NONE, _default=_NONE),
        ]

    def create_rewardables(self) -> List[Handler]:
        reward_handler = (
            handlers.RewardForCollectingItems if self.dense else handlers.RewardForCollectingItemsOnce
        )
        return [reward_handler(self.reward_schedule or {self.target_item: 1})]

    def create_agent_start(self) -> List[Handler]:
        return super().create_agent_start()

    def create_agent_handlers(self) -> List[Handler]:
        return [handlers.AgentQuitFromPossessingItem([dict(type="diamond", amount=1)])]

    def create_server_world_generators(self) -> List[Handler]:
        return [handlers.DefaultWorldGenerator(force_reset=True)]

    def create_server_quit_producers(self) -> List[Handler]:
        return [
            handlers.ServerQuitFromTimeUp(time_limit_ms=self.max_episode_steps * MS_PER_STEP),
            handlers.ServerQuitWhenAnyAgentFinishes(),
        ]

    def create_server_decorators(self) -> List[Handler]:
        return []

    def create_server_initial_conditions(self) -> List[Handler]:
        return [
            handlers.TimeInitialCondition(start_time=6000, allow_passage_of_time=True),
            handlers.SpawningInitialCondition(allow_spawning=True),
        ]

    def is_from_folder(self, folder: str) -> bool:
        return folder == f"o_{self.target_item}"

    def get_docstring(self) -> str:
        return f"Obtain a {self.target_item} starting from survival conditions."

    def determine_success_from_rewards(self, rewards: list) -> bool:
        rewards = set(rewards)
        max_missing = round(len(self.reward_schedule) * 0.1)
        reward_values = [s["reward"] for s in self.reward_schedule]
        return len(rewards.intersection(reward_values)) >= len(reward_values) - max_missing


class CustomObtainDiamond(CustomObtain):
    def __init__(self, dense: bool = False, *args, **kwargs):
        super().__init__(
            *args,
            target_item="diamond",
            dense=dense,
            reward_schedule=[
                dict(type="log", amount=1, reward=1),
                dict(type="planks", amount=1, reward=2),
                dict(type="stick", amount=1, reward=4),
                dict(type="crafting_table", amount=1, reward=4),
                dict(type="wooden_pickaxe", amount=1, reward=8),
                dict(type="cobblestone", amount=1, reward=16),
                dict(type="furnace", amount=1, reward=32),
                dict(type="stone_pickaxe", amount=1, reward=32),
                dict(type="iron_ore", amount=1, reward=64),
                dict(type="iron_ingot", amount=1, reward=128),
                dict(type="iron_pickaxe", amount=1, reward=256),
                dict(type="diamond", amount=1, reward=1024),
            ],
            max_episode_steps=18000,
            **kwargs,
        )

    def is_from_folder(self, folder: str) -> bool:
        return folder == "o_dia"


class CustomObtainIronPickaxe(CustomObtain):
    def __init__(self, dense: bool = False, *args, **kwargs):
        super().__init__(
            *args,
            target_item="iron_pickaxe",
            dense=dense,
            reward_schedule=[
                dict(type="log", amount=1, reward=1),
                dict(type="planks", amount=1, reward=2),
                dict(type="stick", amount=1, reward=4),
                dict(type="crafting_table", amount=1, reward=4),
                dict(type="wooden_pickaxe", amount=1, reward=8),
                dict(type="cobblestone", amount=1, reward=16),
                dict(type="furnace", amount=1, reward=32),
                dict(type="stone_pickaxe", amount=1, reward=32),
                dict(type="iron_ore", amount=1, reward=64),
                dict(type="iron_ingot", amount=1, reward=128),
                dict(type="iron_pickaxe", amount=1, reward=256),
            ],
            max_episode_steps=6000,
            **kwargs,
        )

    def is_from_folder(self, folder: str) -> bool:
        return folder == "o_iron"
