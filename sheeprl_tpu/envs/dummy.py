"""Deterministic dummy environments for tests and dry runs.

Behavioral contract from the reference (``sheeprl/envs/dummy.py:7-103``): a
fixed-length episode of all-zero uint8 CHW image observations and zero reward,
with one env per action-space type. The whole algo test suite runs on these,
so they must be cheap and fully deterministic.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import gymnasium as gym
import numpy as np


class _DummyBase(gym.Env):
    """Shared machinery: zero obs, zero reward, done after ``n_steps``."""

    def __init__(self, size: Tuple[int, int, int], n_steps: int):
        self.observation_space = gym.spaces.Box(0, 255, shape=size, dtype=np.uint8)
        self.reward_range = (-np.inf, np.inf)
        self._n_steps = n_steps
        self._step = 0

    def _obs(self) -> np.ndarray:
        return np.zeros(self.observation_space.shape, dtype=np.uint8)

    def step(self, action):
        done = self._step == self._n_steps
        self._step += 1
        return self._obs(), 0.0, done, False, {}

    def reset(self, seed: Optional[int] = None, options=None):
        self._step = 0
        return self._obs(), {}

    def render(self):  # pragma: no cover - nothing to draw
        return None

    def close(self):
        pass


class ContinuousDummyEnv(_DummyBase):
    """Box action space (reference dummy.py:7-37)."""

    def __init__(self, action_dim: int = 2, size: Tuple[int, int, int] = (3, 64, 64), n_steps: int = 128):
        super().__init__(size, n_steps)
        self.action_space = gym.spaces.Box(-np.inf, np.inf, shape=(action_dim,))


class DiscreteDummyEnv(_DummyBase):
    """Discrete action space; obs are random uint8 on step (reference dummy.py:40-70)."""

    def __init__(self, action_dim: int = 2, size: Tuple[int, int, int] = (3, 64, 64), n_steps: int = 4):
        super().__init__(size, n_steps)
        self.action_space = gym.spaces.Discrete(action_dim)
        self._rng = np.random.default_rng(0)

    def step(self, action):
        done = self._step == self._n_steps
        self._step += 1
        obs = self._rng.integers(0, 256, self.observation_space.shape, dtype=np.uint8)
        return obs, 0.0, done, False, {}


class MultiDiscreteDummyEnv(_DummyBase):
    """MultiDiscrete action space (reference dummy.py:73-103)."""

    def __init__(
        self,
        action_dims: Optional[List[int]] = None,
        size: Tuple[int, int, int] = (3, 64, 64),
        n_steps: int = 128,
    ):
        super().__init__(size, n_steps)
        self.action_space = gym.spaces.MultiDiscrete(action_dims or [2, 2])
