"""Async environment execution plane.

One factory (:func:`make_vector_env` / :func:`make_eval_env`) for every
algorithm entrypoint, one seeding formula (:func:`env_seeds`), and the
shared-memory fault-tolerant worker pool
(:class:`AsyncSharedMemVectorEnv`) behind ``env.vectorization=async``.
See ``howto/async_envs.md``.
"""

from sheeprl_tpu.envs.vector.async_env import AsyncSharedMemVectorEnv
from sheeprl_tpu.envs.vector.factory import (
    env_seeds,
    make_eval_env,
    make_vector_env,
    resolve_vectorization,
    vectorize_thunks,
)
from sheeprl_tpu.envs.vector.shmem import N_SLOTS, SharedStepSlabs

__all__ = [
    "AsyncSharedMemVectorEnv",
    "N_SLOTS",
    "SharedStepSlabs",
    "env_seeds",
    "make_eval_env",
    "make_vector_env",
    "resolve_vectorization",
    "vectorize_thunks",
]
