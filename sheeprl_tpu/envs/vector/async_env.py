"""Fault-tolerant shared-memory async vector env.

The EnvPool/SEED-RL-inspired host half of the actor loop: every sub-env runs
in its own worker process and writes its step results straight into the
preallocated shared blocks of :class:`~sheeprl_tpu.envs.vector.shmem.
SharedStepSlabs`, in the exact ``[num_envs, ...]`` layout the replay buffers
and the staging facade consume. ``step()`` returns numpy *views* into the
current slot — zero copies between the simulator writing an observation and
``ReplayBuffer.add`` landing it in ring storage (the slabs are
double-buffered, so the previous step's views stay valid for the
obs→next_obs pattern every entrypoint uses).

Semantics are bitwise-compatible with ``SyncVectorEnv(...,
autoreset_mode=SAME_STEP)`` — same per-env seeding, same SAME_STEP autoreset
(``final_obs``/``final_info`` emitted on the terminal step), same
``_``-masked info aggregation — which is what the seeded parity tests in
``tests/test_envs/test_vector.py`` pin down.

Fault tolerance (the part gymnasium's ``AsyncVectorEnv`` does not have):

- a worker that crashes (env exception, dead process) or hangs past
  ``worker_timeout_s`` is killed and restarted, and the lost step is replaced
  by an auto-reset of that env — reward 0, not terminated/truncated, with
  ``info["env_worker_restart"]`` flagged (the in-process
  ``RestartOnException`` contract);
- restarts are bounded: past ``max_worker_restarts`` the pool **degrades to
  sync** — every worker is torn down and the envs are rebuilt in-process,
  stepped serially from then on (slow beats dead);
- workers ignore SIGTERM/SIGINT, so a preemption signal (PR-2 path:
  checkpoint, drain, exit) is handled solely by the parent — ``close()``
  drains workers cleanly, with a short join budget when
  ``preemption_requested()`` so the grace window is spent on the checkpoint,
  not on env teardown.

Observability: the collective wait for worker results is a
``Time/env_wait_time`` span (per-phase p50/p95/p99 via obs/hist.py), async
steps and worker restarts are run counters in telemetry.json/live.json, and
every restart fires the flight recorder.
"""

from __future__ import annotations

import atexit
import multiprocessing
import os
import time
import warnings
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import gymnasium as gym
import numpy as np
from gymnasium.vector import AutoresetMode, VectorEnv
from gymnasium.vector.utils import CloudpickleWrapper, batch_space, iterate

from sheeprl_tpu.envs.vector.shmem import N_SLOTS, SharedStepSlabs

__all__ = ["AsyncSharedMemVectorEnv"]

#: extra patience for worker boot (module imports + env build dominate)
_BOOT_TIMEOUT_FLOOR_S = 120.0


def _close_at_exit(env_ref) -> None:
    """atexit hook: a run that crashes between pool construction and
    ``envs.close()`` must not wedge at interpreter exit. multiprocessing's
    own atexit handler SIGTERMs daemon children and then join()s them
    *without timeout* — but the workers ignore SIGTERM by design, so the
    join would block forever. This hook is registered after (= runs before)
    multiprocessing's, closing the pool properly first. Weakref so the hook
    never keeps a collected pool alive; close() is idempotent."""
    env = env_ref()
    if env is not None:
        try:
            env.close()
        except Exception:
            pass
#: seed offset applied per restart so a rebuilt env does not bitwise-replay
#: the episode that crashed it
_RESTART_SEED_STRIDE = 1_000_003


def _worker(
    index: int,
    thunk: CloudpickleWrapper,
    conn,
    slabs: SharedStepSlabs,
    autoreset: bool,
    trace_spec: Optional[Tuple[str, int, str]] = None,
) -> None:
    """Worker loop: build the env, then serve reset/step commands, writing
    results into the shared slot the parent names on each command.

    Per-worker observability (obs/dist): every worker counts its served
    steps and busy seconds and reports them on the ``close`` handshake (the
    parent folds them into the pool's ``envpool_*`` telemetry source), and
    — on instrumented runs with tracing — writes its own clock-aligned
    trace file (``trace_envworker<i>*.jsonl``) so ``tools/trace_view.py``
    shows learner + players + workers on one Perfetto timeline."""
    import signal

    # the parent owns shutdown: a preemption SIGTERM/SIGINT fans out to the
    # process group, and a worker that died mid-drain would turn a clean
    # checkpoint-and-exit into a crashed run
    try:
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        signal.signal(signal.SIGINT, signal.SIG_IGN)
    except (ValueError, OSError):  # pragma: no cover - non-main-thread spawn
        pass

    tracer = None
    if trace_spec is not None:
        try:
            # the obs package is jax-free at import time, so this stays a
            # lightweight import inside the env host process
            from sheeprl_tpu.obs.spans import TraceWriter

            path, pid, name = trace_spec
            tracer = TraceWriter(path, xla_annotations=False, pid=pid, process_name=name)
        except Exception:
            tracer = None
    stats = {"steps": 0, "busy_s": 0.0}

    env: Optional[gym.Env] = None
    try:
        env = thunk()
        obs_view, rew_view, term_view, trunc_view = slabs.views()
        conn.send(("ready", None, None, None))
        while True:
            cmd, payload = conn.recv()
            if cmd == "close":
                try:
                    conn.send(("stats", dict(stats), None, None))
                except (BrokenPipeError, OSError):
                    pass
                break
            slot = payload["slot"]
            if cmd == "reset":
                obs, info = env.reset(seed=payload["seed"], options=payload["options"])
                for key, arr in obs_view.items():
                    arr[slot, index] = obs[key]
                rew_view[slot, index] = 0.0
                term_view[slot, index] = False
                trunc_view[slot, index] = False
                conn.send(("ok", info, None, None))
            elif cmd == "step":
                t0 = time.perf_counter()
                obs, reward, terminated, truncated, info = env.step(payload["action"])
                final_obs = final_info = None
                if autoreset and (terminated or truncated):
                    # SAME_STEP autoreset: the terminal obs/info travel in the
                    # info channel, the slab gets the freshly-reset obs
                    final_obs, final_info = obs, info
                    obs, info = env.reset()
                stats["steps"] += 1
                stats["busy_s"] += time.perf_counter() - t0
                if tracer is not None:
                    tracer.complete("env_step", "env", t0)
                for key, arr in obs_view.items():
                    arr[slot, index] = obs[key]
                rew_view[slot, index] = reward
                term_view[slot, index] = terminated
                trunc_view[slot, index] = truncated
                conn.send(("ok", info, final_obs, final_info))
            else:  # pragma: no cover - protocol bug
                raise RuntimeError(f"unknown worker command {cmd!r}")
    except (KeyboardInterrupt, SystemExit):  # pragma: no cover
        pass
    except Exception as exc:
        try:
            conn.send(("error", f"{type(exc).__name__}: {exc}", None, None))
        except (BrokenPipeError, OSError):
            pass
    finally:
        if tracer is not None:
            try:
                tracer.close()
            except Exception:
                pass
        if env is not None:
            try:
                env.close()
            except Exception:
                pass
        try:
            conn.close()
        except OSError:
            pass


class AsyncSharedMemVectorEnv(VectorEnv):
    """``env.vectorization=async``: one worker process per sub-env, shared-
    memory step results, bounded worker restarts, degrade-to-sync fallback.

    Parameters
    ----------
    env_fns: env thunks (cloudpickled to the workers).
    env_seeds: the canonical per-env seeds the factory computed; used to
        re-seed the replacement env after a worker restart (offset per
        restart so the crashed episode is not replayed verbatim).
    context: multiprocessing start method (never ``fork`` — the parent has
        live jax threads).
    worker_timeout_s: per-step **collective** deadline before a worker
        counts as hung (one step may not take longer than this in total, so
        a shared external stall can fail several workers at once — size it
        for the slowest legitimate step, not the average); ``<= 0`` disables
        the timeout.
    max_worker_restarts: restart budget within a rolling
        ``restart_window_s`` window (the ``RestartOnException`` semantics —
        sparse transient failures over a long run are forgiven); one more
        failure inside the window degrades the pool to in-process sync
        stepping.
    """

    metadata = {"autoreset_mode": AutoresetMode.SAME_STEP}

    def __init__(
        self,
        env_fns: Sequence[Callable[[], gym.Env]],
        env_seeds: Optional[Sequence[int]] = None,
        context: str = "forkserver",
        worker_timeout_s: float = 60.0,
        max_worker_restarts: int = 3,
        restart_window_s: float = 300.0,
        trace_dir: Optional[str] = None,
        pool_name: Optional[str] = None,
    ):
        self.env_fns = list(env_fns)
        self.num_envs = len(self.env_fns)
        # distributed observability (obs/dist): per-worker trace files land
        # under trace_dir when the run is tracing; pool_name keys the pool's
        # telemetry source in the merged per-source breakdown
        self._trace_dir = trace_dir
        self.pool_name = pool_name or f"envpool_{os.getpid()}"
        self.worker_stats: Dict[int, Dict[str, Any]] = {}
        self.worker_timeout_s = float(worker_timeout_s)
        self.max_worker_restarts = int(max_worker_restarts)
        self.restart_window_s = float(restart_window_s)
        #: restart timestamps inside the rolling window (true sliding-window
        #: budget); ``worker_restarts`` stays the lifetime total for telemetry
        self._restart_times: deque = deque()
        self._env_seeds = list(env_seeds) if env_seeds is not None else [None] * self.num_envs
        self._ctx = multiprocessing.get_context(context)

        # spaces from a probe env built (and closed) in the parent — the shm
        # layout must exist before any worker can be spawned
        probe = self.env_fns[0]()
        self.single_observation_space = probe.observation_space
        self.single_action_space = probe.action_space
        self.metadata = dict(getattr(probe, "metadata", {}) or {})
        self.metadata["autoreset_mode"] = AutoresetMode.SAME_STEP
        self.render_mode = getattr(probe, "render_mode", None)
        probe.close()
        del probe
        self.observation_space = batch_space(self.single_observation_space, self.num_envs)
        self.action_space = batch_space(self.single_action_space, self.num_envs)

        self._slabs = SharedStepSlabs(self._ctx, self.single_observation_space, self.num_envs)
        self._obs_view, self._rew_view, self._term_view, self._trunc_view = self._slabs.views()
        self._slot = 0
        self.worker_restarts = 0
        self.degraded_to_sync = False
        self._sync_envs: Optional[List[gym.Env]] = None
        self._closed = False

        self._procs: List[Optional[Any]] = [None] * self.num_envs
        self._conns: List[Optional[Any]] = [None] * self.num_envs
        self._restart_counts = [0] * self.num_envs
        boot = max(self.worker_timeout_s, _BOOT_TIMEOUT_FLOOR_S)
        for i in range(self.num_envs):
            self._spawn_worker(i)
        for i in range(self.num_envs):
            self._await_ready(i, boot)
        atexit.register(_close_at_exit, weakref.ref(self))

    # -- worker lifecycle ---------------------------------------------------

    def _spawn_worker(self, index: int) -> None:
        parent_conn, child_conn = self._ctx.Pipe(duplex=True)
        trace_spec = None
        if self._trace_dir:
            gen = self._restart_counts[index] if hasattr(self, "_restart_counts") else 0
            suffix = f"_g{gen}" if gen else ""
            trace_spec = (
                os.path.join(self._trace_dir, f"trace_envworker{index}{suffix}.jsonl"),
                1000 + index,  # distinct Perfetto track vs learner/players
                f"envworker{index}",
            )
        proc = self._ctx.Process(
            target=_worker,
            name=f"vecenv-worker-{index}",
            args=(
                index,
                CloudpickleWrapper(self.env_fns[index]),
                child_conn,
                self._slabs,
                True,
                trace_spec,
            ),
            daemon=True,
        )
        proc.start()
        child_conn.close()
        self._procs[index] = proc
        self._conns[index] = parent_conn

    def _await_ready(self, index: int, timeout_s: float) -> None:
        conn = self._conns[index]
        if not conn.poll(timeout_s):
            raise TimeoutError(
                f"async env worker {index} did not come up within {timeout_s:.0f}s"
            )
        try:
            msg = conn.recv()
        except (EOFError, OSError) as exc:
            raise RuntimeError(
                f"async env worker {index} died during boot (import/env-build "
                "failure — run with env.vectorization=sync to see the traceback)"
            ) from exc
        if msg[0] != "ready":
            raise RuntimeError(f"async env worker {index} failed during boot: {msg[1]}")

    def _kill_worker(self, index: int) -> None:
        proc, conn = self._procs[index], self._conns[index]
        self._procs[index] = self._conns[index] = None
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
        if proc is not None and proc.is_alive():
            # workers ignore SIGTERM by design (the parent owns preemption),
            # so SIGTERM would only stall here — SIGKILL outright; a killed
            # worker cannot corrupt anything: its slab slot is rewritten by
            # the revive (or degrade) reset
            proc.kill()
            proc.join(timeout=2.0)

    def _restart_seed(self, index: int) -> Optional[int]:
        seed = self._env_seeds[index]
        if seed is None:
            return None
        return int(seed) + self._restart_counts[index] * _RESTART_SEED_STRIDE

    def _note_restart(self, index: int, reason: str) -> None:
        from sheeprl_tpu.obs import counters as _counters
        from sheeprl_tpu.obs.telemetry import get_telemetry

        now = time.monotonic()
        self._restart_times.append(now)
        if self.restart_window_s > 0:
            # true sliding window: only failures clustered inside the last
            # restart_window_s seconds spend the degrade budget — sparse
            # transient failures over a long run are forgiven
            while self._restart_times and now - self._restart_times[0] > self.restart_window_s:
                self._restart_times.popleft()
        self.worker_restarts += 1
        self._restart_counts[index] += 1
        _counters.add_env_worker_restart()
        warnings.warn(
            f"async env worker {index} {reason}; restart "
            f"{len(self._restart_times)}/{self.max_worker_restarts} in window "
            "(auto-reset replaces the lost step)"
        )
        tel = get_telemetry()
        if tel is not None and tel.flight is not None:
            tel.flight.trigger(
                "env_worker_restart",
                {
                    "worker": index,
                    "reason": reason,
                    "restarts": self.worker_restarts,
                    "restarts_in_window": len(self._restart_times),
                    "max_worker_restarts": self.max_worker_restarts,
                },
            )

    def _restart_budget_exhausted(self) -> bool:
        """Degrade when the restarts inside the rolling window (or the
        lifetime total when the window is disabled) exceed the budget."""
        in_budget = (
            len(self._restart_times) if self.restart_window_s > 0 else self.worker_restarts
        )
        return in_budget > self.max_worker_restarts

    def _revive_worker(self, index: int, slot: int) -> Dict[str, Any]:
        """Replace a dead/hung worker and fill its step slot with a reset
        obs (reward 0, not terminated — the ``RestartOnException`` contract);
        returns the info dict for the replacement step."""
        self._kill_worker(index)
        self._spawn_worker(index)
        boot = max(self.worker_timeout_s, _BOOT_TIMEOUT_FLOOR_S)
        self._await_ready(index, boot)
        conn = self._conns[index]
        conn.send(("reset", {"seed": self._restart_seed(index), "options": None, "slot": slot}))
        if not conn.poll(boot):
            raise TimeoutError(f"restarted env worker {index} hung on its first reset")
        msg = conn.recv()
        if msg[0] != "ok":
            raise RuntimeError(f"restarted env worker {index} failed its first reset: {msg[1]}")
        info = dict(msg[1] or {})
        info["env_worker_restart"] = True
        return info

    # -- degrade-to-sync ----------------------------------------------------

    def _degrade_to_sync(self, reason: str) -> None:
        from sheeprl_tpu.obs import counters as _counters
        from sheeprl_tpu.obs.telemetry import get_telemetry

        warnings.warn(
            f"async env pool exceeded its restart budget "
            f"({self.max_worker_restarts}): degrading to in-process sync "
            f"stepping ({reason}); every env is auto-reset in place of the lost step"
        )
        for i in range(self.num_envs):
            self._kill_worker(i)
        self.degraded_to_sync = True
        _counters.add_env_degraded()
        tel = get_telemetry()
        if tel is not None and tel.flight is not None:
            tel.flight.trigger(
                "env_degrade_sync",
                {"reason": reason, "restarts": self.worker_restarts},
            )
        self._sync_envs = [fn() for fn in self.env_fns]
        for i, env in enumerate(self._sync_envs):
            # bump every env's restart generation: resetting a healthy env
            # with its ORIGINAL seed would bitwise-replay trajectories the
            # buffer already holds from run start
            self._restart_counts[i] += 1
            obs, _ = env.reset(seed=self._restart_seed(i))
            for key, arr in self._obs_view.items():
                arr[self._slot, i] = obs[key]
            self._rew_view[self._slot, i] = 0.0
            self._term_view[self._slot, i] = False
            self._trunc_view[self._slot, i] = False

    def _step_sync(self, actions_per_env: List[Any]) -> Dict[int, Tuple]:
        """In-process serial stepping after degrade (same slab layout, same
        SAME_STEP autoreset, so callers never notice beyond the speed)."""
        slot = self._slot
        results: Dict[int, Tuple] = {}
        for i, env in enumerate(self._sync_envs):
            obs, reward, terminated, truncated, info = env.step(actions_per_env[i])
            final_obs = final_info = None
            if terminated or truncated:
                final_obs, final_info = obs, info
                obs, info = env.reset()
            for key, arr in self._obs_view.items():
                arr[slot, i] = obs[key]
            self._rew_view[slot, i] = reward
            self._term_view[slot, i] = terminated
            self._trunc_view[slot, i] = truncated
            results[i] = (info, final_obs, final_info)
        return results

    # -- VectorEnv API ------------------------------------------------------

    def reset(
        self,
        *,
        seed: Optional[Any] = None,
        options: Optional[Dict[str, Any]] = None,
    ):
        self._assert_open()
        if seed is None:
            seeds: List[Optional[int]] = [None] * self.num_envs
        elif isinstance(seed, int):
            seeds = [seed + i for i in range(self.num_envs)]
        else:
            seeds = list(seed)
            assert len(seeds) == self.num_envs
        # flip the slot like step() does, so views from a preceding step
        # survive a reset too (the documented double-buffer contract)
        self._slot = (self._slot + 1) % N_SLOTS
        slot = self._slot
        infos: Dict[str, Any] = {}
        if self._sync_envs is not None:
            for i, env in enumerate(self._sync_envs):
                obs, info = env.reset(seed=seeds[i], options=options)
                for key, arr in self._obs_view.items():
                    arr[slot, i] = obs[key]
                infos = self._add_info(infos, info, i)
        else:
            for i in range(self.num_envs):
                self._conns[i].send(
                    ("reset", {"seed": seeds[i], "options": options, "slot": slot})
                )
            results = self._collect(slot)
            for i in range(self.num_envs):
                infos = self._add_info(infos, results[i][0], i)
        return self._slot_obs(slot), infos

    def step(self, actions):
        self._assert_open()
        actions_per_env = [
            np.asarray(a) for a in iterate(self.action_space, actions)
        ]
        self._slot = (self._slot + 1) % N_SLOTS
        slot = self._slot
        if self._sync_envs is not None:
            results = self._step_sync(actions_per_env)
        else:
            for i in range(self.num_envs):
                self._conns[i].send(("step", {"action": actions_per_env[i], "slot": slot}))
            results = self._collect(slot)
            from sheeprl_tpu.obs import counters as _counters

            _counters.add_env_async_steps(self.num_envs)
        infos: Dict[str, Any] = {}
        for i in range(self.num_envs):
            info, final_obs, final_info = results[i]
            if final_obs is not None or final_info is not None:
                infos = self._add_info(
                    infos, {"final_obs": final_obs, "final_info": final_info}, i
                )
            infos = self._add_info(infos, info, i)
        return (
            self._slot_obs(slot),
            np.copy(self._rew_view[slot]),
            np.copy(self._term_view[slot]),
            np.copy(self._trunc_view[slot]),
            infos,
        )

    def _collect(self, slot: int) -> Dict[int, Tuple]:
        """Gather one reply per worker under the collective step deadline,
        reviving (or degrading past the windowed budget) crashed/hung
        workers. A revived worker is not handed the lost command again — the
        step is replaced by the auto-reset contract.

        The wait is the ``Time/env_wait_time`` span — on a healthy overlap
        run its histogram hugs zero while the accelerator trains; when it
        grows, the envs are the bottleneck again.
        """
        from sheeprl_tpu.obs.spans import span

        deadline = (
            time.perf_counter() + self.worker_timeout_s
            if self.worker_timeout_s > 0
            else None
        )
        results: Dict[int, Tuple] = {}
        failed: List[Tuple[int, str]] = []
        with span("Time/env_wait_time", phase="env_wait"):
            for i in range(self.num_envs):
                conn = self._conns[i]
                remaining = None if deadline is None else max(deadline - time.perf_counter(), 0.0)
                try:
                    if remaining is not None and not conn.poll(remaining):
                        failed.append((i, "hung past worker_timeout_s"))
                        continue
                    msg = conn.recv()
                except (EOFError, BrokenPipeError, ConnectionResetError, OSError):
                    failed.append((i, "process died"))
                    continue
                if msg[0] == "ok":
                    results[i] = (msg[1], msg[2], msg[3])
                else:
                    failed.append((i, f"env raised ({msg[1]})"))
        for i, reason in failed:
            self._note_restart(i, reason)
            if self._restart_budget_exhausted():
                self._degrade_to_sync(reason)
                # the degrade auto-reset EVERY env into the current slot, so
                # every env reports the restart-step contract for this step
                return {
                    j: ({"env_worker_restart": True}, None, None)
                    for j in range(self.num_envs)
                }
            try:
                results[i] = (self._revive_worker(i, slot), None, None)
            except Exception as exc:
                self._degrade_to_sync(f"worker {i} restart failed: {exc}")
                return {
                    j: ({"env_worker_restart": True}, None, None)
                    for j in range(self.num_envs)
                }
        return results

    def _slot_obs(self, slot: int) -> Dict[str, np.ndarray]:
        """Zero-copy: views into the shared slot, ``[num_envs, ...]`` per key."""
        return {key: arr[slot] for key, arr in self._obs_view.items()}

    def _assert_open(self) -> None:
        if self._closed:
            raise RuntimeError("AsyncSharedMemVectorEnv is closed")

    # -- teardown -----------------------------------------------------------

    def close_extras(self, **kwargs) -> None:
        if self._closed:
            return
        self._closed = True
        if self._sync_envs is not None:
            for env in self._sync_envs:
                try:
                    env.close()
                except Exception:
                    pass
            self._sync_envs = None
            self._publish_pool_source()
            return
        try:
            from sheeprl_tpu.ckpt import preemption_requested

            draining = preemption_requested()
        except Exception:  # pragma: no cover - ckpt subsystem absent
            draining = False
        # under preemption the grace window belongs to the final checkpoint:
        # ask workers to exit but only wait briefly before terminating them
        join_budget = 2.0 if draining else 10.0
        for conn in self._conns:
            if conn is not None:
                try:
                    conn.send(("close", {}))
                except (BrokenPipeError, OSError):
                    pass
        deadline = time.perf_counter() + join_budget
        # collect the per-worker stats reply each worker sends on the close
        # handshake (steps served, env busy seconds) — best-effort within
        # the join budget, a dead/hung worker just reports nothing. Drain
        # any unconsumed step/reset replies first (a teardown between
        # dispatch and collect leaves a stale 'ok' queued ahead of 'stats').
        for i, conn in enumerate(self._conns):
            if conn is None:
                continue
            try:
                while True:
                    remaining = max(min(deadline - time.perf_counter(), 1.0), 0.0)
                    if not conn.poll(remaining):
                        break
                    msg = conn.recv()
                    if msg[0] == "stats" and isinstance(msg[1], dict):
                        self.worker_stats[i] = msg[1]
                        break
            except (EOFError, BrokenPipeError, ConnectionResetError, OSError):
                pass
        for proc in self._procs:
            remaining = deadline - time.perf_counter()
            if proc is None or remaining <= 0:
                continue  # budget spent: straight to SIGKILL below
            proc.join(timeout=remaining)
        for i in range(self.num_envs):
            self._kill_worker(i)
        self._publish_pool_source()

    def _publish_pool_source(self) -> None:
        """Fold this pool's per-worker stats into the merged telemetry view
        (obs/dist/aggregate): published into the process-local source
        registry — the learner's telemetry picks it up directly; inside a
        plane player it lands in the player's final sidecar as
        ``env_pools`` and is lifted to ``player<k>/<pool>`` at the merge —
        and mirrored as a sidecar file when a telemetry run dir exists."""
        try:
            from sheeprl_tpu.obs.dist import aggregate as _aggregate
            from sheeprl_tpu.obs.telemetry import get_telemetry

            snap = {
                "num_envs": self.num_envs,
                "worker_restarts": self.worker_restarts,
                "degraded_to_sync": bool(self.degraded_to_sync),
                "workers": {
                    str(i): {
                        "steps": int(self.worker_stats.get(i, {}).get("steps", 0)),
                        "busy_s": round(
                            float(self.worker_stats.get(i, {}).get("busy_s", 0.0)), 3
                        ),
                        "restarts": int(self._restart_counts[i]),
                    }
                    for i in range(self.num_envs)
                },
            }
            _aggregate.publish_source(self.pool_name, snap)
            tel = get_telemetry()
            if tel is not None and tel.run_dir:
                _aggregate.write_sidecar(
                    os.path.join(tel.run_dir, "telemetry"), self.pool_name, snap
                )
        except Exception:
            pass  # telemetry must never break env teardown

    def close(self, **kwargs) -> None:
        self.close_extras(**kwargs)

    def __del__(self):  # pragma: no cover - interpreter teardown best effort
        try:
            if not getattr(self, "_closed", True):
                self.close()
        except Exception:
            pass
