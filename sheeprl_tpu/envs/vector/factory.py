"""The one vector-env factory every algorithm entrypoint builds envs through.

Before this module existed, the ``SyncVectorEnv(thunks, ...)`` block (and its
seeding arithmetic) was copy-pasted across all 17 entrypoints and had already
drifted; the per-algo ``evaluate.py`` files hand-rolled yet another
``make_env(...)()`` single-env path. Now:

- :func:`make_vector_env` is the single train-time constructor — it computes
  the canonical per-env seeds, builds the wrapped thunks via
  :func:`sheeprl_tpu.utils.env.make_env`, and picks the vectorization backend
  from ``env.vectorization``:

  ========== ==============================================================
  ``sync``   (default) gymnasium ``SyncVectorEnv``, SAME_STEP autoreset —
             serial, deterministic, zero processes.
  ``async``  :class:`~sheeprl_tpu.envs.vector.async_env.
             AsyncSharedMemVectorEnv` — one worker process per env writing
             step results into shared memory, per-step timeouts, bounded
             worker restarts, degrade-to-sync (``howto/async_envs.md``).
  ``gym_async`` gymnasium ``AsyncVectorEnv`` (no shared memory, no fault
             tolerance) — kept for envs whose observations the shm layout
             cannot hold.
  ========== ==============================================================

  The legacy ``env.sync_env`` boolean keeps its exact meaning (``True`` →
  sync, ``False`` → gym_async) while ``vectorization`` is unset; an
  explicitly set ``vectorization`` wins.

- :func:`make_eval_env` is the single test-time constructor: one fully
  wrapped env on the same seeding path (seed = ``env_seeds(...)[0]``), so
  evaluation sees bitwise the wrappers/seeding training saw.

- :func:`env_seeds` owns the seeding formula — ``seed + rank * n_envs +
  idx`` — in one place, asserting the per-env seeds are distinct (several
  entrypoints used to compute this inline with slight variations).
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence

import gymnasium as gym

from sheeprl_tpu.utils.env import make_env

__all__ = [
    "env_seeds",
    "make_eval_env",
    "make_vector_env",
    "resolve_backend",
    "resolve_vectorization",
    "vectorize_thunks",
]

_BACKENDS = ("sync", "async", "gym_async")
_ENV_BACKENDS = ("python", "jax")


def resolve_backend(cfg) -> str:
    """``env.backend``: which execution plane serves the training envs.

    ``python`` (default) is the vector-env plane below — gymnasium envs
    stepped by this factory's sync/async backends. ``jax`` is the pure-JAX
    rollout engine (:mod:`sheeprl_tpu.envs.rollout`): env dynamics are jax
    step functions and whole collection bursts run inside one jitted
    ``lax.scan``, writing straight into the device ring. Entrypoints that
    support the jax tier branch on this BEFORE calling
    :func:`make_vector_env`; for the rest, ``make_vector_env`` fails with a
    pointed error rather than silently serving a Python env.
    """
    backend = cfg.env.get("backend", "python") or "python"
    backend = str(backend).lower()
    if backend not in _ENV_BACKENDS:
        raise ValueError(f"env.backend must be one of {_ENV_BACKENDS}, got {backend!r}")
    return backend


def env_seeds(seed: int, rank: int, n_envs: int) -> List[int]:
    """Canonical per-env seeds: ``seed + rank * n_envs + idx``.

    ``rank`` is the *process* index (``fabric.global_rank``) and ``n_envs``
    the per-process env count, so ranks never overlap and rank 0 reproduces
    the historical single-process ``seed + idx`` bitwise.
    """
    seeds = [int(seed) + int(rank) * int(n_envs) + idx for idx in range(int(n_envs))]
    assert len(set(seeds)) == len(seeds), f"per-env seeds must be distinct, got {seeds}"
    return seeds


def resolve_vectorization(cfg) -> str:
    """The backend for this run.

    An explicitly set ``env.vectorization`` (non-null — the shipped default
    is null) always wins, ``sync`` included: ``env=diambra
    env.vectorization=sync`` must get the serial backend even though that
    recipe ships ``sync_env: False``, and ``vectorization=async`` must
    reach the shared-memory pool. When unset, the legacy ``env.sync_env``
    keeps its exact historical meaning (``True`` → sync, ``False`` →
    gym_async); with neither set, sync (determinism)."""
    mode = cfg.env.get("vectorization", None)
    legacy = cfg.env.get("sync_env", None)
    if mode is not None:
        mode = str(mode).lower()
        if mode not in _BACKENDS:
            raise ValueError(
                f"env.vectorization must be one of {_BACKENDS}, got {mode!r}"
            )
        if legacy is not None and bool(legacy) != (mode == "sync"):
            import warnings

            warnings.warn(
                f"env.vectorization={mode} overrides legacy env.sync_env={bool(legacy)}"
            )
        return mode
    if legacy is not None:
        return "sync" if legacy else "gym_async"
    return "sync"


def _build_thunks(
    cfg,
    rank: int,
    n_envs: int,
    log_dir: Optional[str],
    prefix: str,
    restart_on_exception: bool,
) -> List[Callable[[], gym.Env]]:
    seeds = env_seeds(cfg.seed, rank, n_envs)
    thunks: List[Callable[[], gym.Env]] = []
    for idx in range(n_envs):
        # vector_env_idx carries the global env index (rank-offset) so the
        # wrapper `rank` kwarg and the capture-video gate (env 0 of rank 0,
        # the only rank handed a log_dir) keep their historical meaning
        thunk = make_env(
            cfg,
            seeds[idx],
            0,
            log_dir,
            prefix,
            vector_env_idx=rank * n_envs + idx,
        )
        if restart_on_exception:
            from functools import partial

            from sheeprl_tpu.envs.wrappers import RestartOnException

            thunk = partial(RestartOnException, thunk)
        thunks.append(thunk)
    return thunks


def vectorize_thunks(
    thunks: Sequence[Callable[[], gym.Env]],
    cfg,
    env_seeds_list=None,
    log_dir: Optional[str] = None,
    rank: int = 0,
):
    """Wrap prebuilt thunks in the configured vector backend (the factory's
    lower half — diagnostics/tools that need custom thunks enter here)."""
    mode = resolve_vectorization(cfg)
    if mode == "sync":
        from gymnasium.vector import AutoresetMode, SyncVectorEnv

        return SyncVectorEnv(thunks, autoreset_mode=AutoresetMode.SAME_STEP)
    # worker processes use a NON-fork start method (default ``forkserver``,
    # override via ``env.mp_context``): this process is multithreaded the
    # moment jax initializes its backends, and os.fork() of a multithreaded
    # parent can deadlock the child
    context = str(cfg.env.get("mp_context", "forkserver") or "forkserver")
    if mode == "async":
        from sheeprl_tpu.envs.vector.async_env import AsyncSharedMemVectorEnv

        # distributed observability (obs/dist): on tracing runs each worker
        # writes its own clock-aligned trace file under <log_dir>/telemetry,
        # and the pool reports per-worker stats as source `envpool_r<rank>`
        trace_dir = None
        try:
            from sheeprl_tpu.obs.spans import get_tracer
            from sheeprl_tpu.obs.telemetry import get_telemetry

            tel = get_telemetry()
            tracer = get_tracer()
            tracing = (tel is not None and tel.trace_enabled) or (
                # plane player processes run no Telemetry but do carry a
                # file-backed tracer (plane/worker.child_main) — their env
                # workers trace alongside it
                tel is None and tracer is not None and tracer.path
            )
            if tracing and log_dir:
                trace_dir = os.path.join(log_dir, "telemetry")
        except Exception:
            trace_dir = None
        return AsyncSharedMemVectorEnv(
            thunks,
            env_seeds=env_seeds_list,
            context=context,
            worker_timeout_s=float(cfg.env.get("worker_timeout_s", 60.0) or 0.0),
            max_worker_restarts=int(cfg.env.get("max_worker_restarts", 3)),
            restart_window_s=float(cfg.env.get("restart_window_s", 300.0) or 0.0),
            trace_dir=trace_dir,
            pool_name=f"envpool_r{int(rank)}",
        )
    from gymnasium.vector import AsyncVectorEnv, AutoresetMode

    return AsyncVectorEnv(thunks, autoreset_mode=AutoresetMode.SAME_STEP, context=context)


def make_vector_env(
    cfg,
    fabric=None,
    log_dir: Optional[str] = None,
    prefix: str = "train",
    restart_on_exception: bool = False,
    n_envs: Optional[int] = None,
    rank: Optional[int] = None,
):
    """Build the train-time vector env for one process.

    ``n_envs`` defaults to ``env.num_envs * fabric.world_size`` (the
    per-process env count every entrypoint uses — world_size is the device
    count, and one process drives the whole mesh). ``log_dir`` is only handed
    to the envs on global rank zero, preserving the video/logging gate the
    entrypoints used to spell out inline.

    ``rank`` overrides the seed-partition index (default
    ``fabric.global_rank``): the actor–learner plane's player processes pass
    their player index with per-player ``n_envs``, so N players slice the
    same canonical ``env_seeds`` sequence one learner process would use —
    player 0 of a 1-player plane reproduces the thread-local seeding bitwise.
    """
    if resolve_backend(cfg) == "jax":
        raise ValueError(
            "env.backend=jax requested, but this algorithm's train loop only "
            "supports the Python vector-env plane (the pure-JAX rollout "
            "engine currently integrates with: sac). Drop env.backend=jax, "
            "or use a supported entrypoint (sheeprl_tpu/envs/rollout)."
        )
    if rank is None:
        rank = int(fabric.global_rank) if fabric is not None else 0
    rank = int(rank)
    if n_envs is None:
        world_size = int(fabric.world_size) if fabric is not None else 1
        n_envs = int(cfg.env.num_envs) * world_size
    is_zero = fabric.is_global_zero if fabric is not None else rank == 0
    thunks = _build_thunks(
        cfg,
        rank,
        n_envs,
        log_dir if is_zero else None,
        prefix,
        restart_on_exception,
    )
    return vectorize_thunks(
        thunks,
        cfg,
        env_seeds_list=env_seeds(cfg.seed, rank, n_envs),
        log_dir=log_dir if is_zero else None,
        rank=rank,
    )


def make_eval_env(
    cfg,
    log_dir: Optional[str],
    prefix: str = "test",
    rank: int = 0,
) -> gym.Env:
    """One fully wrapped single env for evaluation/test episodes — the same
    wrapper pipeline and the same canonical seed (env 0 of ``rank``) the
    train-time factory would produce."""
    seed = env_seeds(cfg.seed, rank, 1)[0]
    return make_env(cfg, seed, 0, log_dir, prefix, vector_env_idx=0)()
