"""Double-buffered shared-memory slabs for the async vector env.

One anonymous shared block (``multiprocessing.RawArray``, inherited by worker
processes at spawn — no named /dev/shm segments to leak or unlink) per
observation key plus one each for reward / terminated / truncated, laid out as

    ``[n_slots, num_envs, *single_shape]``

with ``n_slots=2``: workers write step *k* into slot ``k % 2`` while the
arrays the caller received for step *k-1* (views into the other slot) stay
valid. That is what makes the zero-copy contract safe for the standard RL
loop — ``obs`` from the previous step and ``real_next_obs`` from the current
one never alias the same buffer, and the one copy on the whole path is
``ReplayBuffer.add`` writing into its ring storage.

Only ``Dict`` observation spaces with array-typed leaves (``Box`` /
``Discrete`` / ``MultiDiscrete`` / ``MultiBinary``) are supported — exactly
what :func:`sheeprl_tpu.utils.env.make_env` produces for every configured
environment.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import gymnasium as gym
import numpy as np

__all__ = ["SharedStepSlabs", "N_SLOTS"]

#: two slots: the previous step's views survive the current step's writes
N_SLOTS = 2

_SUPPORTED_LEAVES = (
    gym.spaces.Box,
    gym.spaces.Discrete,
    gym.spaces.MultiDiscrete,
    gym.spaces.MultiBinary,
)


def _leaf_spec(space: gym.Space) -> Tuple[Tuple[int, ...], np.dtype]:
    if not isinstance(space, _SUPPORTED_LEAVES):
        raise TypeError(
            f"AsyncSharedMemVectorEnv supports array-typed observation leaves, "
            f"got {type(space).__name__}; use env.vectorization=sync for this env"
        )
    return tuple(space.shape), np.dtype(space.dtype)


def _alloc(ctx, shape: Tuple[int, ...], dtype: np.dtype):
    """One RawArray sized in bytes; viewed through np.frombuffer on each side."""
    nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    return ctx.RawArray("b", max(nbytes, 1))


def _view(raw, shape: Tuple[int, ...], dtype: np.dtype) -> np.ndarray:
    return np.frombuffer(raw, dtype=dtype, count=int(np.prod(shape, dtype=np.int64))).reshape(shape)


class SharedStepSlabs:
    """The step-result blocks shared between the parent and every worker.

    Picklable by construction (holds only RawArrays and plain metadata), so
    the whole object is passed to each worker as a ``Process`` arg; both
    sides call :meth:`views` once and index ``[slot, env_idx]`` thereafter.
    """

    def __init__(self, ctx, single_observation_space: gym.spaces.Dict, num_envs: int):
        if not isinstance(single_observation_space, gym.spaces.Dict):
            raise TypeError(
                "AsyncSharedMemVectorEnv requires a Dict observation space "
                f"(make_env always produces one), got {type(single_observation_space).__name__}"
            )
        self.num_envs = int(num_envs)
        self._specs: Dict[str, Tuple[Tuple[int, ...], np.dtype]] = {
            key: _leaf_spec(space) for key, space in single_observation_space.spaces.items()
        }
        self._obs_raw = {
            key: _alloc(ctx, (N_SLOTS, num_envs, *shape), dtype)
            for key, (shape, dtype) in self._specs.items()
        }
        # float64 rewards and bool flags: bitwise-identical to SyncVectorEnv's
        # step buffers (np.zeros(num_envs) / dtype=np.bool_)
        self._rew_raw = _alloc(ctx, (N_SLOTS, num_envs), np.dtype(np.float64))
        self._term_raw = _alloc(ctx, (N_SLOTS, num_envs), np.dtype(np.bool_))
        self._trunc_raw = _alloc(ctx, (N_SLOTS, num_envs), np.dtype(np.bool_))

    def views(self) -> Tuple[Dict[str, np.ndarray], np.ndarray, np.ndarray, np.ndarray]:
        """Numpy views over the shared blocks: ``(obs, rewards, terminated,
        truncated)``, each leading with ``[n_slots, num_envs]``."""
        n = self.num_envs
        obs = {
            key: _view(raw, (N_SLOTS, n, *self._specs[key][0]), self._specs[key][1])
            for key, raw in self._obs_raw.items()
        }
        rewards = _view(self._rew_raw, (N_SLOTS, n), np.dtype(np.float64))
        terminated = _view(self._term_raw, (N_SLOTS, n), np.dtype(np.bool_))
        truncated = _view(self._trunc_raw, (N_SLOTS, n), np.dtype(np.bool_))
        return obs, rewards, terminated, truncated

    def raw_nbytes(self) -> int:
        """Allocated shared bytes (telemetry/debug)."""
        total = len(self._rew_raw) + len(self._term_raw) + len(self._trunc_raw)
        total += sum(len(raw) for raw in self._obs_raw.values())
        return total
