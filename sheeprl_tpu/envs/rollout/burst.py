"""Burst acting for Python envs (rollout tier b).

The per-step acting path pays one policy dispatch per env step:
``policy_fn(...)`` → ``np.asarray(actions)`` → ``envs.step(...)`` — and on
a remote-attached accelerator each dispatch is a network round trip.
:class:`BurstActor` compiles K acting steps into ONE dispatched program: a
``lax.while_loop`` whose body runs the policy on device and hands the
actions to the host through an ordered
:func:`jax.experimental.io_callback`. The host callback is the *whole* old
loop body — ``envs.step`` (against the PR-5 shared-memory obs slabs),
episode bookkeeping, the replay-buffer ``add`` — and returns the prepared
next observation for the following in-loop act.

The burst length is a *traced scalar*, not a static loop bound: every K
runs the SAME compiled program, just with a different trip count. That is
what makes trajectories bitwise-independent of K (asserted for every
converted family in ``tests/test_envs/test_rollout.py``) — with one
program per length, XLA inlines the trip-count-1 loop and the changed
fusion context perturbs the acting math by an ulp, which a seeded bitwise
gate catches. One program also means one trace/compile, however often the
train-gating clamps vary the burst length mid-run.

So the data still crosses the link every step (the envs are Python), but
the per-step *dispatch* — trace-cache lookup, program launch, host sync on
the action fetch — is paid once per burst: ``K = env.act_burst`` acts per
dispatch. With ``K = 1`` this is the old per-step path, same key
discipline and the same trajectories; larger K trades train/log/checkpoint
*cadence granularity* (gates run per burst, not per step) for dispatch
amortization — see ``howto/rollout_engine.md``.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import numpy as np

from sheeprl_tpu.obs.counters import add_rollout_burst

__all__ = ["BurstActor"]


class BurstActor:
    """Dispatch K acting steps as one jitted program.

    ``act_fn(params, obs, key) -> (callback_args, key)`` is the traced
    policy body — ``callback_args`` a tuple of arrays handed to the host.
    ``host_step(*np_args) -> next_obs`` is the Python loop body: it steps
    the vector env, does every piece of host bookkeeping (buffer add,
    episode logging, info stashing), and returns the prepared obs pytree
    for the next act. ``obs_example`` fixes the obs spec (shapes/dtypes the
    callback must return exactly).
    """

    def __init__(
        self,
        act_fn: Callable[[Any, Any, Any], Tuple[Tuple[Any, ...], Any]],
        host_step: Callable[..., Any],
        obs_example: Any,
    ):
        import jax

        self._act_fn = act_fn
        self._host_step = host_step
        self._obs_spec = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(np.asarray(x).shape, np.asarray(x).dtype),
            obs_example,
        )
        self._rollout_fn: Any = None
        self._device: Any = None

    @staticmethod
    def _params_device(params):
        """The device the acting params are committed to (first by id for
        mesh-replicated trees); CPU when nothing is committed (numpy trees)."""
        import jax

        for leaf in jax.tree_util.tree_leaves(params):
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None:
                devices = sorted(sharding.device_set, key=lambda d: d.id)
                if devices:
                    return devices[0]
        try:
            return jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            return jax.devices()[0]

    def _build(self):
        import jax
        import jax.numpy as jnp
        from jax.experimental import io_callback

        act_fn = self._act_fn
        host_step = self._host_step
        obs_spec = self._obs_spec

        def rollout(params, obs, key, n):
            # n is traced: one compiled program serves every burst length,
            # so the acting math cannot depend on K (bitwise K-invariance)
            def cond(carry):
                i, _, _ = carry
                return i < n

            def body(carry):
                i, obs, key = carry
                cb_args, key = act_fn(params, obs, key)
                # ordered: env steps must run in sequence, and the next act
                # consumes exactly this step's observation
                next_obs = io_callback(host_step, obs_spec, *cb_args, ordered=True)
                return (i + jnp.int32(1), next_obs, key)

            _, obs, key = jax.lax.while_loop(cond, body, (jnp.int32(0), obs, key))
            return obs, key

        return jax.jit(rollout)

    def rollout(self, params: Any, obs: Any, key: Any, burst_len: int) -> Tuple[Any, Any]:
        """Run ``burst_len`` acting steps with one device dispatch; returns
        ``(next_obs, key)`` after the burst. The host sees every step via
        ``host_step`` exactly as the per-step loop would have."""
        import jax

        burst_len = int(burst_len)
        if self._rollout_fn is None:
            self._rollout_fn = self._build()
        fn = self._rollout_fn
        # The burst program must be SINGLE-device: this jax version's SPMD
        # sharding propagation CHECK-aborts on io_callback programs with
        # multi-device (mesh-replicated) inputs. Pin to wherever the acting
        # params already live — the CPU host mirror when player_on_host is
        # on, the accelerator otherwise (algo.player_on_host=False keeps
        # its meaning) — so the put is a no-op except for mesh-replicated
        # params, which collapse to their first device's local shard.
        if self._device is None:
            self._device = self._params_device(params)
        params, obs, key = jax.device_put((params, obs, key), self._device)
        obs, key = fn(params, obs, key, np.int32(burst_len))
        # FENCE: dispatch is async — the caller is about to read host state
        # the callbacks mutate (replay buffer, episode stats). The returned
        # obs is data-dependent on the LAST ordered callback, so readiness
        # here proves every host_step of the burst has run.
        jax.block_until_ready(obs)
        add_rollout_burst(act_dispatches=1)
        return obs, key
