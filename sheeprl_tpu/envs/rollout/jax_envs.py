"""Pure-JAX environments: dynamics as jit-traceable step functions.

The gymnax/brax contract — an env is a pair of pure functions over an
explicit state pytree::

    state, obs                                  = env.reset(key)
    state, obs, reward, terminated, truncated   = env.step(state, action, key)

Both are single-env; the rollout engine vmaps them over the env batch and
scans them under jit, so an entire collection burst (act → step → ring add)
is one device program. Time limits live inside the state (an ``elapsed``
counter) so truncation — and therefore SAME_STEP-style auto-reset — is
traceable too.

Two native envs ship with the framework, bitwise ports of the gymnasium
classic-control dynamics (asserted against gymnasium in
``tests/test_envs/test_rollout.py``):

- :class:`JaxCartPole` — ``CartPole-v1``: discrete actions, the benchmark
  headline env (reference ``benchmark.py`` protocol).
- :class:`JaxPendulum` — ``Pendulum-v1``: continuous actions, the SAC-family
  recipe env.

:class:`BraxEnvAdapter` wraps any Brax env into the same contract when brax
is importable (the container does not bake it in; the adapter raises a
pointed error otherwise instead of failing at import time).

Observations are exposed as a single ``"state"`` vector (a Dict space with
one MLP key), matching how the vector-obs algos (SAC, PPO-mlp) consume the
gymnasium envs through the wrapper pipeline.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "BraxEnvAdapter",
    "JaxCartPole",
    "JaxPendulum",
    "jax_env_ids",
    "make_jax_env",
]


class JaxVectorizableEnv:
    """Base contract: single-env pure functions + gym spaces for agent setup."""

    #: single-env observation space, a Dict with one "state" MLP key so the
    #: vector-obs algos see the same structure the gym wrapper pipeline builds
    observation_space: gym.spaces.Dict
    #: single-env action space
    action_space: gym.Space
    #: episode step limit baked into the state's `elapsed` counter
    max_episode_steps: int

    def reset(self, key: jax.Array) -> Tuple[Any, jax.Array]:
        raise NotImplementedError

    def step(
        self, state: Any, action: jax.Array, key: jax.Array
    ) -> Tuple[Any, jax.Array, jax.Array, jax.Array, jax.Array]:
        raise NotImplementedError

    def sample_action(self, key: jax.Array) -> jax.Array:
        """Uniform action draw (the in-jit analog of ``action_space.sample()``
        for prefill phases)."""
        raise NotImplementedError


class JaxCartPole(JaxVectorizableEnv):
    """``CartPole-v1`` dynamics as pure jax (gymnasium classic_control port).

    Euler integration at tau=0.02; termination at |x| > 2.4 or |theta| >
    ~12deg; reward 1.0 every step (including the terminal one); truncation at
    500 steps; reset state uniform in (-0.05, 0.05)^4.
    """

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    TOTAL_MASS = MASSCART + MASSPOLE
    LENGTH = 0.5  # half-pole length
    POLEMASS_LENGTH = MASSPOLE * LENGTH
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_THRESHOLD = 12 * 2 * np.pi / 360
    X_THRESHOLD = 2.4

    def __init__(self, max_episode_steps: int = 500):
        self.max_episode_steps = int(max_episode_steps)
        high = np.array(
            [self.X_THRESHOLD * 2, np.inf, self.THETA_THRESHOLD * 2, np.inf],
            dtype=np.float32,
        )
        self.observation_space = gym.spaces.Dict(
            {"state": gym.spaces.Box(-high, high, (4,), np.float32)}
        )
        self.action_space = gym.spaces.Discrete(2)

    def reset(self, key: jax.Array):
        phys = jax.random.uniform(key, (4,), jnp.float32, -0.05, 0.05)
        state = {"phys": phys, "elapsed": jnp.int32(0)}
        return state, phys

    def step(self, state, action, key):
        x, x_dot, theta, theta_dot = (state["phys"][i] for i in range(4))
        force = jnp.where(action.reshape(()) == 1, self.FORCE_MAG, -self.FORCE_MAG)
        costheta = jnp.cos(theta)
        sintheta = jnp.sin(theta)
        temp = (force + self.POLEMASS_LENGTH * theta_dot**2 * sintheta) / self.TOTAL_MASS
        thetaacc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASSPOLE * costheta**2 / self.TOTAL_MASS)
        )
        xacc = temp - self.POLEMASS_LENGTH * thetaacc * costheta / self.TOTAL_MASS
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * xacc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * thetaacc
        phys = jnp.stack([x, x_dot, theta, theta_dot]).astype(jnp.float32)
        elapsed = state["elapsed"] + 1
        terminated = (
            (x < -self.X_THRESHOLD)
            | (x > self.X_THRESHOLD)
            | (theta < -self.THETA_THRESHOLD)
            | (theta > self.THETA_THRESHOLD)
        )
        truncated = elapsed >= self.max_episode_steps
        reward = jnp.float32(1.0)
        return {"phys": phys, "elapsed": elapsed}, phys, reward, terminated, truncated

    def sample_action(self, key: jax.Array) -> jax.Array:
        return jax.random.randint(key, (), 0, 2, jnp.int32)


class JaxPendulum(JaxVectorizableEnv):
    """``Pendulum-v1`` dynamics as pure jax (gymnasium classic_control port).

    Continuous torque in [-2, 2]; never terminates; truncation at 200 steps;
    obs = [cos(theta), sin(theta), theta_dot]; reset theta uniform in
    [-pi, pi], theta_dot uniform in [-1, 1].
    """

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0

    def __init__(self, max_episode_steps: int = 200):
        self.max_episode_steps = int(max_episode_steps)
        high = np.array([1.0, 1.0, self.MAX_SPEED], dtype=np.float32)
        self.observation_space = gym.spaces.Dict(
            {"state": gym.spaces.Box(-high, high, (3,), np.float32)}
        )
        self.action_space = gym.spaces.Box(
            -self.MAX_TORQUE, self.MAX_TORQUE, (1,), np.float32
        )

    @staticmethod
    def _obs(th, thdot):
        return jnp.stack([jnp.cos(th), jnp.sin(th), thdot]).astype(jnp.float32)

    def reset(self, key: jax.Array):
        hi = jnp.array([np.pi, 1.0], jnp.float32)
        th, thdot = jax.random.uniform(key, (2,), jnp.float32, -hi, hi)
        state = {"th": th, "thdot": thdot, "elapsed": jnp.int32(0)}
        return state, self._obs(th, thdot)

    def step(self, state, action, key):
        th, thdot = state["th"], state["thdot"]
        u = jnp.clip(action.reshape(()), -self.MAX_TORQUE, self.MAX_TORQUE)
        norm_th = ((th + jnp.pi) % (2 * jnp.pi)) - jnp.pi
        cost = norm_th**2 + 0.1 * thdot**2 + 0.001 * u**2
        newthdot = thdot + (
            3.0 * self.G / (2.0 * self.L) * jnp.sin(th)
            + 3.0 / (self.M * self.L**2) * u
        ) * self.DT
        newthdot = jnp.clip(newthdot, -self.MAX_SPEED, self.MAX_SPEED)
        newth = th + newthdot * self.DT
        elapsed = state["elapsed"] + 1
        truncated = elapsed >= self.max_episode_steps
        new_state = {"th": newth, "thdot": newthdot, "elapsed": elapsed}
        return (
            new_state,
            self._obs(newth, newthdot),
            -cost.astype(jnp.float32),
            jnp.bool_(False),
            truncated,
        )

    def sample_action(self, key: jax.Array) -> jax.Array:
        return jax.random.uniform(
            key, (1,), jnp.float32, -self.MAX_TORQUE, self.MAX_TORQUE
        )


class BraxEnvAdapter(JaxVectorizableEnv):
    """Adapt a Brax env (``brax.envs.get_environment``) to the contract.

    Brax episodes carry no intrinsic time limit — the adapter adds the same
    ``elapsed`` counter the native envs use. Gated on brax being importable:
    the pinned container does not ship it, so construction (not import) is
    the point of failure, with a message naming the extra dependency.
    """

    def __init__(self, env_name: str, max_episode_steps: int = 1000, **brax_kwargs):
        try:
            from brax import envs as brax_envs
        except ImportError as exc:  # pragma: no cover - container has no brax
            raise ImportError(
                f"env.backend=jax with id 'brax/{env_name}' needs the optional "
                "brax package, which this container does not bake in; use a "
                f"native pure-JAX env ({sorted(_NATIVE)}) or install brax"
            ) from exc
        self._env = brax_envs.get_environment(env_name, **brax_kwargs)
        self.max_episode_steps = int(max_episode_steps)
        obs_size = int(self._env.observation_size)
        act_size = int(self._env.action_size)
        self.observation_space = gym.spaces.Dict(
            {"state": gym.spaces.Box(-np.inf, np.inf, (obs_size,), np.float32)}
        )
        self.action_space = gym.spaces.Box(-1.0, 1.0, (act_size,), np.float32)

    def reset(self, key: jax.Array):
        brax_state = self._env.reset(key)
        state = {"brax": brax_state, "elapsed": jnp.int32(0)}
        return state, brax_state.obs.astype(jnp.float32)

    def step(self, state, action, key):
        del key  # brax dynamics are deterministic given the state
        brax_state = self._env.step(state["brax"], action)
        elapsed = state["elapsed"] + 1
        terminated = brax_state.done.astype(bool).reshape(())
        truncated = elapsed >= self.max_episode_steps
        new_state = {"brax": brax_state, "elapsed": elapsed}
        return (
            new_state,
            brax_state.obs.astype(jnp.float32),
            brax_state.reward.astype(jnp.float32).reshape(()),
            terminated,
            truncated,
        )

    def sample_action(self, key: jax.Array) -> jax.Array:
        return jax.random.uniform(
            key, self.action_space.shape, jnp.float32, -1.0, 1.0
        )


_NATIVE: Dict[str, Callable[..., JaxVectorizableEnv]] = {
    "CartPole-v1": JaxCartPole,
    "Pendulum-v1": JaxPendulum,
}


def jax_env_ids() -> Tuple[str, ...]:
    """Ids the pure-JAX backend can serve natively (brax ids are
    ``brax/<name>`` and resolve dynamically)."""
    return tuple(sorted(_NATIVE))


def make_jax_env(
    env_id: str, max_episode_steps: Optional[int] = None
) -> JaxVectorizableEnv:
    """Resolve ``env.id`` to a pure-JAX env for ``env.backend=jax``.

    Native ids map to the built-in dynamics; ``brax/<name>`` goes through
    :class:`BraxEnvAdapter`. Anything else fails with the supported list —
    the python backend is the fallback for every other env.
    """
    kwargs = {} if max_episode_steps is None else {"max_episode_steps": int(max_episode_steps)}
    if env_id in _NATIVE:
        return _NATIVE[env_id](**kwargs)
    if env_id.startswith("brax/"):
        return BraxEnvAdapter(env_id.split("/", 1)[1], **kwargs)
    raise ValueError(
        f"env.backend=jax cannot serve env.id={env_id!r}: pure-JAX dynamics "
        f"exist for {sorted(_NATIVE)} (and 'brax/<name>' with brax "
        "installed); drop env.backend=jax to run it through the Python "
        "vector-env plane"
    )
