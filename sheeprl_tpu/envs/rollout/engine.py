"""Jitted-scan collection for pure-JAX envs (rollout tier a).

One :meth:`JaxRolloutEngine.collect` call runs an entire collection burst —
policy inference, env dynamics, SAME_STEP auto-reset, and the replay-ring
append — as ONE device program: a ``lax.scan`` over the burst length whose
final step scatters every collected transition into the PR-3 device ring
(:func:`sheeprl_tpu.data.device_ring.scatter_append`). Zero host
involvement per burst: no per-step action download, no per-step obs upload,
no per-step buffer add. This is the SEED-RL accelerator-side acting pattern
with the env itself on the accelerator (EnvPool taken to its limit).

The engine stores transitions in the flat SAC-style layout
(``observations``/``actions``/``rewards``/``dones`` +
``next_observations`` when ``store_next_obs``), bitwise what the host loop
builds: ``next_observations`` is the PRE-reset obs of the step (the
SAME_STEP ``final_obs`` contract) while the carried obs is the reset obs.

Determinism: the key discipline is fixed (one action key per step for the
whole batch — matching the host policy path — then per-env step and reset
keys), so a jitted burst of T steps is bitwise a host loop of T single
steps with the same key; asserted in ``tests/test_envs/test_rollout.py``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from sheeprl_tpu.data.device_ring import DeviceRingTransitions, scatter_append
from sheeprl_tpu.envs.rollout.jax_envs import JaxVectorizableEnv
from sheeprl_tpu.obs.counters import add_rollout_burst

__all__ = ["JaxRolloutEngine"]


def _where_done(done, val_if_done, val_else):
    """Per-env select with broadcast over trailing dims."""
    import jax.numpy as jnp

    mask = done.reshape(done.shape + (1,) * (val_if_done.ndim - done.ndim))
    return jnp.where(mask, val_if_done, val_else)


class JaxRolloutEngine:
    """Own the env batch state and the jitted collection programs.

    ``policy(params, obs, key) -> actions`` acts on the whole ``[n_envs,
    obs_dim]`` batch with one key (the same contract as the per-step policy
    fns in the SAC/PPO entrypoints). ``ring`` is a single-shard
    :class:`DeviceRingTransitions`; when ``None`` the burst returns the
    stacked transition rows instead (tests / throughput probes).
    """

    def __init__(
        self,
        env: JaxVectorizableEnv,
        n_envs: int,
        key: Any,
        policy: Optional[Callable[[Any, Any, Any], Any]] = None,
        ring: Optional[DeviceRingTransitions] = None,
        store_next_obs: bool = True,
        obs_key: str = "observations",
    ):
        import jax

        self.env = env
        self.n_envs = int(n_envs)
        self.ring = ring
        self.store_next_obs = bool(store_next_obs)
        self.obs_key = str(obs_key)
        self._policy = policy
        self._collect_fns: Dict[Tuple[int, bool, bool], Any] = {}
        obs_space = env.observation_space["state"]
        self._obs_dim = int(np.prod(obs_space.shape))
        self._act_len = int(np.prod(env.action_space.shape)) if env.action_space.shape else 1
        self._reset_all = jax.jit(
            lambda k: jax.vmap(env.reset)(jax.random.split(k, self.n_envs))
        )
        self._carry = None
        self._key = key
        # fixed row shapes: built once, reused by every jit_state/adopt pair
        self._example_rows = self._build_example_rows()

    # -- surface the entrypoints build agents from --------------------------

    @property
    def single_observation_space(self):
        return self.env.observation_space

    @property
    def single_action_space(self):
        return self.env.action_space

    def example_rows(self) -> Dict[str, np.ndarray]:
        """Zero-valued ``[n_envs, ...]`` per-env rows in the stored layout —
        what the ring allocates its device storage from."""
        return self._example_rows

    def _build_example_rows(self) -> Dict[str, np.ndarray]:
        rows = {
            "observations": np.zeros((self.n_envs, self._obs_dim), np.float32),
            "actions": np.zeros((self.n_envs, self._act_len), np.float32),
            "rewards": np.zeros((self.n_envs, 1), np.float32),
            "dones": np.zeros((self.n_envs, 1), np.float32),
        }
        if self.store_next_obs:
            rows["next_observations"] = rows["observations"].copy()
        return rows

    def reset(self) -> None:
        """(Re)initialize the env batch: per-env reset keys derived from the
        engine key, episode accumulators zeroed."""
        import jax
        import jax.numpy as jnp

        self._key, sub = jax.random.split(self._key)
        state, obs = self._reset_all(sub)
        self._carry = (
            state,
            obs.reshape(self.n_envs, self._obs_dim).astype(jnp.float32),
            self._key,
            jnp.zeros(self.n_envs, jnp.float32),  # episode return
            jnp.zeros(self.n_envs, jnp.int32),  # episode length
        )

    # -- the jitted burst ----------------------------------------------------

    def _build_collect(self, burst_len: int, random_actions: bool, with_ring: bool):
        import jax
        import jax.numpy as jnp

        env = self.env
        n = self.n_envs
        policy = self._policy
        store_next = self.store_next_obs
        obs_dim, act_len = self._obs_dim, self._act_len
        capacity = int(self.ring.buffer_size) if with_ring else 0

        def body(params, carry, _):
            state, obs, key, ep_ret, ep_len = carry
            key, akey = jax.random.split(key)
            if random_actions:
                actions = jax.vmap(env.sample_action)(jax.random.split(akey, n))
            else:
                actions = policy(params, obs, akey)
            key, skey, rkey = jax.random.split(key, 3)
            state2, nobs, rew, term, trunc = jax.vmap(env.step)(
                state, actions, jax.random.split(skey, n)
            )
            nobs = nobs.reshape(n, obs_dim).astype(jnp.float32)
            done = jnp.logical_or(term, trunc)
            row = {
                "observations": obs,
                "actions": actions.reshape(n, act_len).astype(jnp.float32),
                "rewards": rew.reshape(n, 1).astype(jnp.float32),
                "dones": done.reshape(n, 1).astype(jnp.float32),
            }
            if store_next:
                # PRE-reset obs: the SAME_STEP final_obs contract
                row["next_observations"] = nobs
            # auto-reset: done envs restart; live envs keep their state
            reset_state, reset_obs = jax.vmap(env.reset)(jax.random.split(rkey, n))
            state3 = jax.tree_util.tree_map(
                lambda r, s: _where_done(done, r, s), reset_state, state2
            )
            obs_next = _where_done(done, reset_obs.reshape(n, obs_dim), nobs)
            ep_ret = ep_ret + rew.reshape(n)
            ep_len = ep_len + 1
            stats = (rew.reshape(n), done.reshape(n), ep_ret, ep_len)
            ep_ret = jnp.where(done.reshape(n), 0.0, ep_ret)
            ep_len = jnp.where(done.reshape(n), 0, ep_len)
            return (state3, obs_next, key, ep_ret, ep_len), (row, stats)

        if with_ring:

            def collect(params, carry, bufs, pos):
                import functools

                carry, (rows, stats) = jax.lax.scan(
                    functools.partial(body, params), carry, None, length=burst_len
                )
                bufs = scatter_append(bufs, pos, rows, capacity)
                pos = (pos + burst_len) % capacity
                return carry, bufs, pos, stats

            return jax.jit(collect, donate_argnums=(2,))

        def collect_rows(params, carry):
            import functools

            carry, (rows, stats) = jax.lax.scan(
                functools.partial(body, params), carry, None, length=burst_len
            )
            return carry, rows, stats

        return jax.jit(collect_rows)

    def collect(self, params: Any, burst_len: int, random_actions: bool = False):
        """Run one jitted collection burst of ``burst_len`` steps.

        With a ring: transitions land in the device ring (the host buffer's
        counters advance via ``adopt_jit_state``) and the per-step
        ``(rewards, dones, ep_returns, ep_lengths)`` device arrays — each
        ``[burst_len, n_envs]`` — are returned for episode logging. Without
        a ring: returns ``(rows, stats)`` with the stacked transition rows.
        ``params`` is a jit argument, so a refreshed actor never recompiles
        (pass ``0`` on random bursts).
        """
        if self._carry is None:
            self.reset()
        if not random_actions and self._policy is None:
            raise ValueError(
                "JaxRolloutEngine was built without a policy; pass "
                "random_actions=True or construct it with policy=..."
            )
        burst_len = int(burst_len)
        with_ring = self.ring is not None
        fn_key = (burst_len, bool(random_actions), with_ring)
        fn = self._collect_fns.get(fn_key)
        if fn is None:
            fn = self._build_collect(burst_len, bool(random_actions), with_ring)
            self._collect_fns[fn_key] = fn
        if random_actions:
            params = 0  # unused traced placeholder: keeps one jit signature
        if not with_ring:
            carry, rows, stats = fn(params, self._carry)
            self._carry = carry
            add_rollout_burst(act_dispatches=1, jax_steps=burst_len * self.n_envs)
            return rows, stats
        bufs, pos = self.ring.jit_state(self.example_rows())
        carry, bufs, pos, stats = fn(params, self._carry, bufs, pos)
        self._carry = carry
        self.ring.adopt_jit_state(bufs, burst_len, self.example_rows())
        add_rollout_burst(act_dispatches=1, jax_steps=burst_len * self.n_envs)
        return stats
