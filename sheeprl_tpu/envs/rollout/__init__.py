"""On-device rollout engine: the two-tier acting plane behind the factory.

Round-5 benchmarks left one architectural loss standing: every env step paid
one host→device round trip for action inference (SAC full-protocol e2e at
0.153x the reference baseline), even though replay staging (PR 3) and env
execution (PR 5) were already framework planes. This package closes the loop
with the SEED-RL / EnvPool acting pattern, in two tiers:

- **Tier (a) — pure-JAX envs** (:mod:`jax_envs`, :mod:`engine`): envs whose
  dynamics are ``(state, action, key) -> (state, obs, reward, ...)`` jax
  functions (a native CartPole/Pendulum, plus a Brax adapter). The whole
  act→step→buffer-add loop runs inside ONE ``lax.scan`` under jit, writing
  collection bursts straight into the PR-3 device ring via its in-jit
  :func:`~sheeprl_tpu.data.device_ring.scatter_append` — zero host
  involvement for an entire burst. Selected with ``env.backend=jax``.
- **Tier (b) — Python envs** (:mod:`burst`): the acting loop body (policy →
  env.step → buffer bookkeeping) is compiled as a K-step ``lax.scan`` whose
  env step is an ordered ``io_callback`` into the host — K sequential acts
  against the shared-memory obs slabs with ONE device dispatch per burst
  (``K = env.act_burst``), instead of one dispatch per step.

Telemetry: each burst bumps ``rollout_bursts``/``act_dispatches`` (and
``env_steps_jax`` for tier a) and runs under the ``Time/rollout_time`` span
(phase ``rollout``). See ``howto/rollout_engine.md``.
"""

from sheeprl_tpu.envs.rollout.burst import BurstActor
from sheeprl_tpu.envs.rollout.engine import JaxRolloutEngine
from sheeprl_tpu.envs.rollout.jax_envs import (
    JaxCartPole,
    JaxPendulum,
    jax_env_ids,
    make_jax_env,
)

__all__ = [
    "BurstActor",
    "JaxCartPole",
    "JaxPendulum",
    "JaxRolloutEngine",
    "jax_env_ids",
    "make_jax_env",
]
