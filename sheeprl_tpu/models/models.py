"""Neural building blocks, flax.linen edition.

TPU-native re-design of the reference model layer (``sheeprl/models/models.py``:
MLP :15, CNN :121, DeCNN :204, NatureCNN :287, LayerNormGRUCell :330,
MultiEncoder :405, MultiDecoder :463, helpers in ``sheeprl/utils/model.py``).
Differences that matter on TPU:

- convolutions run NHWC (XLA's native TPU layout); the modules accept the
  env-layer's channel-first ``[..., C, H, W]`` observations and transpose at
  the module boundary, so the rest of the stack keeps the reference's CHW
  convention while the MXU sees its preferred layout.
- no shape probing with dummy forwards (reference NatureCNN :311-314) — output
  shapes are static math.
- arbitrary batch shape folding (reference ``cnn_forward`` utils/model.py:164)
  is a plain reshape here since linen modules are shape-polymorphic over
  leading dims by construction.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.models.norm import FastLayerNorm

# the fused-kernel registry (sheeprl_tpu/kernels, howto/kernels.md): the
# recurrent cells below dispatch their gate math through it so one
# `algo.fused_kernels` knob swaps reference / padded-XLA / Pallas tiers
from sheeprl_tpu import kernels

# ---------------------------------------------------------------------------
# activation resolution (accepts jax-style names and torch-style class paths,
# so reference config trees run unchanged)
# ---------------------------------------------------------------------------

_ACTIVATIONS: Dict[str, Callable] = {
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "elu": jax.nn.elu,
    "gelu": jax.nn.gelu,
    "leaky_relu": jax.nn.leaky_relu,
    "sigmoid": jax.nn.sigmoid,
    "softplus": jax.nn.softplus,
    "identity": lambda x: x,
    "none": lambda x: x,
}

_TORCH_NAMES = {
    "torch.nn.Tanh": "tanh",
    "torch.nn.ReLU": "relu",
    "torch.nn.ReLU6": "relu6",
    "torch.nn.SiLU": "silu",
    "torch.nn.ELU": "elu",
    "torch.nn.GELU": "gelu",
    "torch.nn.LeakyReLU": "leaky_relu",
    "torch.nn.Sigmoid": "sigmoid",
    "torch.nn.Softplus": "softplus",
    "torch.nn.Identity": "identity",
}


def resolve_activation(act: Union[str, Callable, None]) -> Callable:
    if act is None:
        return lambda x: x
    if callable(act):
        return act
    name = _TORCH_NAMES.get(act, act).lower()
    if name not in _ACTIVATIONS:
        raise ValueError(f"Unknown activation '{act}'. Known: {sorted(_ACTIVATIONS)}")
    return _ACTIVATIONS[name]


def _broadcast(value: Any, n: int) -> Tuple:
    """Per-layer argument broadcast (reference create_layers, utils/model.py:90-138)."""
    if isinstance(value, (list, tuple)):
        if len(value) != n:
            raise ValueError(f"Expected {n} per-layer values, got {len(value)}")
        return tuple(value)
    return tuple(value for _ in range(n))


from sheeprl_tpu.distributions.distributions import symlog as _symlog


# ---------------------------------------------------------------------------
# MLP
# ---------------------------------------------------------------------------


class MLP(nn.Module):
    """Dense stack of Linear→[LayerNorm]→activation→[dropout] miniblocks.

    Mirrors the reference MLP (models.py:15-118): hidden miniblocks followed by
    a bare Linear head when ``output_dim`` is set. ``flatten_dim`` folds
    trailing feature dims before the first Linear (reference ``flatten_dim``
    semantics); ``symlog_inputs`` applies the DV3 input transform.
    """

    hidden_sizes: Sequence[int] = ()
    output_dim: Optional[int] = None
    activation: Union[str, Callable] = "relu"
    layer_norm: Union[bool, Sequence[bool]] = False
    norm_eps: float = 1e-5
    dropout: float = 0.0
    flatten_dim: Optional[int] = None
    symlog_inputs: bool = False
    bias: Union[bool, Sequence[bool]] = True
    param_dtype: Any = jnp.float32
    dtype: Optional[Any] = None  # compute dtype (bf16-mixed); params stay param_dtype

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        if self.flatten_dim is not None:
            x = jnp.reshape(x, x.shape[: self.flatten_dim] + (-1,))
        if self.symlog_inputs:
            x = _symlog(x)
        n = len(self.hidden_sizes)
        norms = _broadcast(self.layer_norm, n)
        biases = _broadcast(self.bias, n)
        act = resolve_activation(self.activation)
        ln_idx = 0
        for i, size in enumerate(self.hidden_sizes):
            x = nn.Dense(
                size, use_bias=biases[i], param_dtype=self.param_dtype, dtype=self.dtype
            )(x)
            if norms[i]:
                # FastLayerNorm named like nn.LayerNorm's auto-scheme so
                # checkpoints are unaffected (models/norm.py)
                x = FastLayerNorm(
                    epsilon=self.norm_eps, param_dtype=self.param_dtype, dtype=self.dtype,
                    name=f"LayerNorm_{ln_idx}",
                )(x)
                ln_idx += 1
            x = act(x)
            if self.dropout > 0.0:
                x = nn.Dropout(self.dropout, deterministic=deterministic)(x)
        if self.output_dim is not None:
            x = nn.Dense(self.output_dim, param_dtype=self.param_dtype, dtype=self.dtype)(x)
        return x


# ---------------------------------------------------------------------------
# CNN / DeCNN
# ---------------------------------------------------------------------------


def _to_nhwc(x: jnp.ndarray) -> Tuple[jnp.ndarray, Tuple[int, ...]]:
    """[..., C, H, W] → [N, H, W, C] with leading dims folded."""
    lead = x.shape[:-3]
    c, h, w = x.shape[-3:]
    x = jnp.reshape(x, (-1, c, h, w))
    return jnp.transpose(x, (0, 2, 3, 1)), lead


def _from_nhwc(x: jnp.ndarray, lead: Tuple[int, ...]) -> jnp.ndarray:
    """[N, H, W, C] → [..., C, H, W] restoring leading dims."""
    x = jnp.transpose(x, (0, 3, 1, 2))
    return jnp.reshape(x, lead + x.shape[1:])


class CNN(nn.Module):
    """Conv2d stack (reference CNN, models.py:121-203). Input ``[..., C, H, W]``.

    Runs NHWC internally. ``flatten`` returns ``[..., features]``.
    """

    channels: Sequence[int]
    kernel_sizes: Union[int, Sequence[int]] = 3
    strides: Union[int, Sequence[int]] = 1
    paddings: Union[int, str, Sequence[Any]] = 0
    activation: Union[str, Callable] = "relu"
    layer_norm: Union[bool, Sequence[bool]] = False
    norm_eps: float = 1e-5
    bias: Union[bool, Sequence[bool]] = True
    flatten: bool = False
    param_dtype: Any = jnp.float32
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        n = len(self.channels)
        ks = _broadcast(self.kernel_sizes, n)
        st = _broadcast(self.strides, n)
        pd = _broadcast(self.paddings, n)
        norms = _broadcast(self.layer_norm, n)
        biases = _broadcast(self.bias, n)
        act = resolve_activation(self.activation)
        x, lead = _to_nhwc(x)
        ln_idx = 0
        for i, ch in enumerate(self.channels):
            pad = pd[i] if isinstance(pd[i], str) else [(pd[i], pd[i])] * 2
            x = nn.Conv(
                ch,
                kernel_size=(ks[i], ks[i]),
                strides=(st[i], st[i]),
                padding=pad,
                use_bias=biases[i],
                param_dtype=self.param_dtype,
                dtype=self.dtype,
            )(x)
            if norms[i]:
                # LayerNorm over the channel axis — NHWC makes the reference's
                # LayerNormChannelLast permute dance (utils/model.py:225-235)
                # free; FastLayerNorm = one-pass custom-VJP backward
                x = FastLayerNorm(
                    epsilon=self.norm_eps, param_dtype=self.param_dtype, dtype=self.dtype,
                    name=f"LayerNorm_{ln_idx}",
                )(x)
                ln_idx += 1
            x = act(x)
        if self.flatten:
            x = jnp.reshape(x, (x.shape[0], -1))
            return jnp.reshape(x, lead + x.shape[1:])
        return _from_nhwc(x, lead)


class DeCNN(nn.Module):
    """ConvTranspose2d stack (reference DeCNN, models.py:204-284). Input ``[..., C, H, W]``."""

    channels: Sequence[int]
    kernel_sizes: Union[int, Sequence[int]] = 3
    strides: Union[int, Sequence[int]] = 1
    paddings: Union[int, Sequence[int]] = 0
    activation: Union[str, Callable] = "relu"
    layer_norm: Union[bool, Sequence[bool]] = False
    norm_eps: float = 1e-5
    bias: Union[bool, Sequence[bool]] = True
    final_activation: Union[str, Callable, None] = None
    param_dtype: Any = jnp.float32
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        n = len(self.channels)
        ks = _broadcast(self.kernel_sizes, n)
        st = _broadcast(self.strides, n)
        pd = _broadcast(self.paddings, n)
        norms = _broadcast(self.layer_norm, n)
        biases = _broadcast(self.bias, n)
        act = resolve_activation(self.activation)
        x, lead = _to_nhwc(x)
        ln_idx = 0
        for i, ch in enumerate(self.channels):
            # configs carry torch-style transposed-conv padding p
            # (out = (in-1)*s - 2p + k); flax's padding is the forward conv's,
            # so p maps to (k-1-p) per side
            if isinstance(pd[i], str):
                pad = pd[i]
            else:
                f = ks[i] - 1 - pd[i]
                pad = [(f, f)] * 2
            x = nn.ConvTranspose(
                ch,
                kernel_size=(ks[i], ks[i]),
                strides=(st[i], st[i]),
                padding=pad,
                use_bias=biases[i],
                transpose_kernel=True,
                param_dtype=self.param_dtype,
                dtype=self.dtype,
            )(x)
            if norms[i]:
                x = FastLayerNorm(
                    epsilon=self.norm_eps, param_dtype=self.param_dtype, dtype=self.dtype,
                    name=f"LayerNorm_{ln_idx}",
                )(x)
                ln_idx += 1
            if i < n - 1:
                x = act(x)
            elif self.final_activation is not None:
                x = resolve_activation(self.final_activation)(x)
        return _from_nhwc(x, lead)


class NatureCNN(nn.Module):
    """DQN-Nature encoder (reference models.py:287-327): 3 convs + Linear head.

    Output feature size is static math, no dummy-forward probe.
    """

    features_dim: int = 512
    screen_size: int = 64
    activation: Union[str, Callable] = "relu"
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        act = resolve_activation(self.activation)
        x, lead = _to_nhwc(x)
        for ch, k, s in ((32, 8, 4), (64, 4, 2), (64, 3, 1)):
            x = nn.Conv(ch, kernel_size=(k, k), strides=(s, s), padding="VALID", param_dtype=self.param_dtype)(x)
            x = act(x)
        x = jnp.reshape(x, (x.shape[0], -1))
        x = act(nn.Dense(self.features_dim, param_dtype=self.param_dtype)(x))
        return jnp.reshape(x, lead + x.shape[1:])


# ---------------------------------------------------------------------------
# LayerNormGRUCell
# ---------------------------------------------------------------------------


class LayerNormGRUCell(nn.Module):
    """Hafner-style GRU cell (reference models.py:330-402, after dreamerv2 nets.py:317).

    One joint Linear over ``[h, x]`` → LayerNorm → (reset, cand, update) with
    ``cand = tanh(reset * cand)`` and the update gate biased by −1. This is the
    recurrent core of the RSSM; the time loop lives *outside* in a
    ``jax.lax.scan`` so XLA fuses the whole sequence.
    """

    hidden_size: int
    bias: bool = True
    layer_norm: bool = False
    norm_eps: float = 1e-3
    param_dtype: Any = jnp.float32
    dtype: Optional[Any] = None
    #: resolved kernel tier ("off" | "xla" | "pallas") — set at agent-build
    #: time via kernels.resolve_tier(cfg.algo.fused_kernels); "off" is the
    #: reference flax path, bitwise the pre-registry cell
    fused: str = "off"

    @nn.compact
    def __call__(self, x: jnp.ndarray, h: jnp.ndarray) -> jnp.ndarray:
        if self.fused == "off" or self.is_initializing():
            # reference path (also the init path, so parameter names/shapes
            # never depend on the tier): gate math lives in kernels/reference
            inp = jnp.concatenate([h, x], axis=-1)
            z = nn.Dense(
                3 * self.hidden_size, use_bias=self.bias, param_dtype=self.param_dtype,
                dtype=self.dtype,
            )(inp)
            if self.layer_norm:
                z = FastLayerNorm(
                    epsilon=self.norm_eps, param_dtype=self.param_dtype, dtype=self.dtype,
                    name="LayerNorm_0",
                )(z)
            return kernels.reference.hafner_gates(z, h)
        params = self.variables["params"]
        dense = params["Dense_0"]
        ln = params.get("LayerNorm_0") if self.layer_norm else None
        return kernels.hafner_gru_cell(
            h,
            x,
            dense["kernel"],
            dense.get("bias") if self.bias else None,
            ln["scale"] if ln is not None else None,
            ln["bias"] if ln is not None else None,
            hidden_size=self.hidden_size,
            eps=float(self.norm_eps),
            tier=self.fused,
        )


class FusedGRUCell(nn.Module):
    """flax ``nn.GRUCell`` with its gate math routed through the kernel
    registry (DreamerV1's recurrent core). Parameter tree, initializers and
    the ``(carry, inputs) -> (new_carry, out)`` signature are identical to
    ``nn.GRUCell`` — ``fused="off"`` is bitwise the flax module, so swapping
    it in changes no checkpoint and no result.
    """

    features: int
    param_dtype: Any = jnp.float32
    fused: str = "off"

    @nn.compact
    def __call__(self, carry: jnp.ndarray, inputs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        h = carry
        if self.fused == "off" or self.is_initializing():
            def dense_i(name):
                return nn.Dense(self.features, use_bias=True, param_dtype=self.param_dtype, name=name)

            def dense_h(name, use_bias=False):
                return nn.Dense(
                    self.features, use_bias=use_bias, param_dtype=self.param_dtype,
                    kernel_init=nn.initializers.orthogonal(), name=name,
                )

            new_h = kernels.reference.flax_gru_gates(
                dense_i("ir")(inputs), dense_i("iz")(inputs), dense_i("in")(inputs),
                dense_h("hr")(h), dense_h("hz")(h), dense_h("hn", use_bias=True)(h), h,
            )
        else:
            new_h = kernels.flax_gru_cell(
                h, inputs, self.variables["params"], hidden_size=self.features, tier=self.fused
            )
        return new_h, new_h


# ---------------------------------------------------------------------------
# MultiEncoder / MultiDecoder
# ---------------------------------------------------------------------------


class MultiEncoder(nn.Module):
    """Fuse cnn and mlp sub-encoders by feature concat (reference models.py:405-460).

    ``cnn_keys`` observations are stacked along channels and encoded once;
    ``mlp_keys`` observations are concatenated and encoded once; the two
    feature vectors are concatenated.
    """

    cnn_encoder: Optional[nn.Module] = None
    mlp_encoder: Optional[nn.Module] = None
    cnn_keys: Sequence[str] = ()
    mlp_keys: Sequence[str] = ()

    def __call__(self, obs: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        feats = []
        if self.cnn_encoder is not None and self.cnn_keys:
            x = jnp.concatenate([obs[k] for k in self.cnn_keys], axis=-3)
            feats.append(self.cnn_encoder(x))
        if self.mlp_encoder is not None and self.mlp_keys:
            x = jnp.concatenate([obs[k] for k in self.mlp_keys], axis=-1)
            feats.append(self.mlp_encoder(x))
        if not feats:
            raise ValueError("MultiEncoder needs at least one of cnn_keys / mlp_keys")
        return jnp.concatenate(feats, axis=-1) if len(feats) > 1 else feats[0]


class MultiDecoder(nn.Module):
    """Per-key reconstruction dict (reference models.py:463-489)."""

    cnn_decoder: Optional[nn.Module] = None
    mlp_decoder: Optional[nn.Module] = None
    cnn_keys: Sequence[str] = ()
    mlp_keys: Sequence[str] = ()
    cnn_channels: Sequence[int] = ()  # per-key channel counts for the split
    mlp_dims: Sequence[int] = ()      # per-key feature dims for the split

    def __call__(self, latent: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        out: Dict[str, jnp.ndarray] = {}
        if self.cnn_decoder is not None and self.cnn_keys:
            if len(self.cnn_keys) > 1 and len(self.cnn_channels) != len(self.cnn_keys):
                raise ValueError(
                    f"MultiDecoder: {len(self.cnn_keys)} cnn_keys need {len(self.cnn_keys)} "
                    f"cnn_channels for the split, got {len(self.cnn_channels)}"
                )
            rec = self.cnn_decoder(latent)
            if len(self.cnn_keys) > 1:
                parts = jnp.split(rec, np.cumsum(self.cnn_channels)[:-1], axis=-3)
            else:
                parts = [rec]
            out.update({k: v for k, v in zip(self.cnn_keys, parts)})
        if self.mlp_decoder is not None and self.mlp_keys:
            if len(self.mlp_keys) > 1 and len(self.mlp_dims) != len(self.mlp_keys):
                raise ValueError(
                    f"MultiDecoder: {len(self.mlp_keys)} mlp_keys need {len(self.mlp_keys)} "
                    f"mlp_dims for the split, got {len(self.mlp_dims)}"
                )
            rec = self.mlp_decoder(latent)
            if len(self.mlp_keys) > 1:
                parts = jnp.split(rec, np.cumsum(self.mlp_dims)[:-1], axis=-1)
            else:
                parts = [rec]
            out.update({k: v for k, v in zip(self.mlp_keys, parts)})
        return out


def per_layer_ortho_init_weights(
    params, gain: float = 1.0, bias: float = 0.0, key=None
):
    """Re-initialize every 2-D kernel in ``params`` orthogonally and set
    biases to a constant (reference utils/model.py:141-161, which recurses
    over torch containers; flax params are already one tree so this is a
    single tree_map). Conv kernels are orthogonalized over the flattened
    receptive field. Returns the new param tree."""
    import jax

    key = jax.random.PRNGKey(0) if key is None else key
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    keys = jax.random.split(key, max(len(flat), 1))
    init = jax.nn.initializers.orthogonal(scale=gain)

    def path_str(path):
        return "/".join(getattr(p, "key", str(p)) for p in path)

    new = {}
    for k, (path, leaf) in zip(keys, flat):
        p = path_str(path)
        if p.endswith("kernel") and leaf.ndim >= 2:
            flat2d = (int(np.prod(leaf.shape[:-1])), leaf.shape[-1])
            new[p] = init(k, flat2d, leaf.dtype).reshape(leaf.shape)
        elif p.endswith("bias"):
            new[p] = jnp.full_like(leaf, bias)
        else:
            new[p] = leaf
    import jax.tree_util as jtu

    return jtu.tree_map_with_path(lambda path, leaf: new[path_str(path)], params)
