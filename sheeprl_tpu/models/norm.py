"""LayerNorm with a hand-derived one-pass backward (``jax.custom_vjp``).

Why this exists (round-4, VERDICT item #4): the DV3 S-preset profile puts
~2.3 ms of the 14.03 ms device step in LayerNorm *backward* lane reductions
across the conv stacks — XLA autodiffs flax's ``nn.LayerNorm`` into a chain
that re-derives the variance path and schedules several cross-lane
reductions per instance. The canonical LN backward needs exactly two row
reductions:

    dx = rstd * (g*γ - mean(g*γ) - x̂ * mean(g*γ * x̂))

computed here from residuals ``(x̂, rstd)`` saved by the forward. Everything
is plain ``jnp`` — no Pallas, deliberately: the round-2/3 fused-kernel
experiments showed XLA cannot overlap async weight prefetches across a
custom-call region, so per-layer custom calls lose their standalone wins to
scheduling barriers. A ``custom_vjp`` keeps the math inside XLA's fusion
domain.

``FastLayerNorm`` is parameter-compatible with ``nn.LayerNorm`` (same
``scale``/``bias`` names and shapes): swapping it in changes no checkpoint.

Memory trade (deliberate, account for it in HBM capacity planning): the
forward saves ``x̂`` as a **float32** residual per LN instance, so under
bf16 training each LayerNorm retains ~4 bytes/element of activation memory
that flax's autodiff backward could rematerialize instead. At the S preset
this is noise; at L/XL presets alongside the device replay ring it is part
of the activation footprint the ``DeviceRingReplay`` HBM guard must leave
headroom for (wrap training in ``jax.checkpoint`` over the scan if it ever
binds — the residual then lives only inside one scan segment).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

__all__ = ["fast_layer_norm", "FastLayerNorm"]


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def fast_layer_norm(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray, eps: float):
    """LayerNorm over the last axis. Statistics always computed in float32
    from the ORIGINAL-precision input (like flax's ``_compute_stats``);
    returns float32 — the caller casts to its compute dtype."""
    return _ln_fwd(x, scale, bias, eps)[0]


def _ln_fwd(x, scale, bias, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (xf - mu) * rstd
    y = xhat * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    # zero-size dtype token: the bwd must emit dx in x's exact dtype
    return y, (xhat, rstd, scale, jnp.zeros((0,), x.dtype))


def _ln_bwd(eps, res, g):
    xhat, rstd, scale, x_dtype_token = res
    gf = g.astype(jnp.float32)
    # parameter grads reduce over every leading (row) axis
    row_axes = tuple(range(g.ndim - 1))
    dbias = jnp.sum(gf, axis=row_axes)
    dscale = jnp.sum(gf * xhat, axis=row_axes)
    gg = gf * scale.astype(jnp.float32)
    m1 = jnp.mean(gg, axis=-1, keepdims=True)
    m2 = jnp.mean(gg * xhat, axis=-1, keepdims=True)
    dx = rstd * (gg - m1 - xhat * m2)
    return (
        dx.astype(x_dtype_token.dtype),
        dscale.astype(scale.dtype),
        dbias.astype(scale.dtype),
    )


fast_layer_norm.defvjp(_ln_fwd, _ln_bwd)


class FastLayerNorm(nn.Module):
    """Drop-in for ``nn.LayerNorm`` (last-axis, affine) with the one-pass
    custom-VJP backward. Parameter names/shapes match ``nn.LayerNorm``, and
    the dtype contract mirrors flax: stats from the original-precision
    input, output in ``dtype`` (or the promotion of input and param dtypes
    when ``dtype`` is None)."""

    epsilon: float = 1e-6
    dtype: Optional[jnp.dtype] = None
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        features = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (features,), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (features,), self.param_dtype)
        out_dtype = self.dtype or jnp.promote_types(x.dtype, self.param_dtype)
        y = fast_layer_norm(x, scale, bias, float(self.epsilon))
        return y.astype(out_dtype)
