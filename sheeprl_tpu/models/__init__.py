from sheeprl_tpu.models.models import (
    CNN,
    MLP,
    DeCNN,
    FusedGRUCell,
    LayerNormGRUCell,
    MultiDecoder,
    MultiEncoder,
    NatureCNN,
    resolve_activation,
)

__all__ = [
    "CNN",
    "MLP",
    "DeCNN",
    "FusedGRUCell",
    "LayerNormGRUCell",
    "MultiDecoder",
    "MultiEncoder",
    "NatureCNN",
    "resolve_activation",
]
