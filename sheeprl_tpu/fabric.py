"""The TPU runtime: mesh, precision, collectives, checkpoint I/O, callbacks.

This is the TPU-native replacement for Lightning Fabric as the reference uses
it (SURVEY §1 L5; ``sheeprl/cli.py:93,139,156``, ``ppo.py:96-201``). The
design is SPMD-first instead of process-per-rank:

- **One process drives all local chips.** The reference spawns one process per
  device and wraps modules in DDP; here a single :class:`Fabric` owns a
  ``jax.sharding.Mesh`` over every device (all hosts) with a ``data`` axis.
  Train steps are jitted with batch inputs sharded over ``data``; XLA inserts
  the gradient ``psum`` (the DDP allreduce) over ICI automatically from the
  shardings. Multi-host runs use ``jax.distributed`` — same code, the mesh
  just spans hosts and collectives ride ICI within a slice / DCN across.
- **"rank" semantics.** ``world_size`` is the number of devices in the mesh
  (matches the reference's world_size = #ranks = #devices); ``global_rank``
  is the *process* index, used only for host-side concerns (logging,
  checkpoint ownership, video capture). Per-rank batch/env counts from the
  reference configs are interpreted per-device, preserving the step-accounting
  contract (``howto/work_with_steps.md``).
- ``fabric.load`` restores both the ``sheeprl_tpu/ckpt`` manifest layout
  (checksum-verified npz shards) and legacy Orbax pytree checkpoints;
  ``fabric.save`` remains the legacy synchronous Orbax writer — train loops
  checkpoint through ``fabric.call("on_checkpoint_*")``, which routes into
  the async, atomic checkpoint subsystem (reference callback.py).
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sheeprl_tpu.parallel import mesh as _mesh
from sheeprl_tpu.parallel import shard as _shard


def _select_devices(devices: Any, accelerator: str) -> List[jax.Device]:
    """Resolve the device list from the fabric config.

    ``devices`` may be "auto" (all), an int (first N), or a list of indices.
    ``accelerator`` ∈ {auto, cpu, gpu, cuda, tpu} picks the jax platform; on a
    machine without that platform we fall back to the default platform with a
    warning (the reference warns similarly for cpu/ddp mismatches).
    """
    platform = None
    accelerator = (accelerator or "auto").lower()
    if accelerator in ("tpu", "gpu", "cuda", "cpu"):
        platform = {"cuda": "gpu"}.get(accelerator, accelerator)
    try:
        all_devices = jax.devices(platform) if platform else jax.devices()
    except RuntimeError:
        warnings.warn(f"No '{platform}' platform available; using the default jax platform")
        all_devices = jax.devices()
    if devices in (None, "auto", -1, "-1"):
        return list(all_devices)
    if isinstance(devices, (list, tuple)):
        return [all_devices[i] for i in devices]
    n = int(devices)
    if n > len(all_devices):
        raise ValueError(f"Requested {n} devices but only {len(all_devices)} are available")
    return list(all_devices[:n])


def compute_dtype_from_precision(precision: Any):
    """The one precision→compute-dtype mapping (shared by Fabric and the
    model builders): "32-true" → None (f32 everywhere), "bf16-mixed" → bf16
    compute with f32 params/losses. Anything else raises — silently
    reinterpreting fp16/true-bf16 requests would mislead."""
    p = str(precision or "32-true").lower()
    if p in ("32-true", "32"):
        return None
    if p == "bf16-mixed":
        return jnp.bfloat16
    raise ValueError(
        f"Unsupported fabric.precision {precision!r}: use '32-true' or 'bf16-mixed'"
    )


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize ``jax.distributed`` for multi-host meshes (the TPU-native
    replacement for the reference's NCCL/Gloo process groups, SURVEY §5.8).

    MUST run before anything touches a jax backend (the CLI calls it right
    after config composition when ``fabric.num_nodes > 1``); once backends
    are up it is a no-op reporting the current state. On TPU pods the
    runtime auto-discovers topology, so all arguments stay ``None``; jax
    itself honors ``JAX_COORDINATOR_ADDRESS`` & friends for everything
    else. Launch one process per host — collectives then ride ICI within a
    slice and DCN across hosts with the same SPMD program. Returns True
    when a multi-process runtime is (or already was) up.
    """
    import jax.distributed

    try:
        from jax._src import xla_bridge

        backends_up = xla_bridge.backends_are_initialized()
    except Exception:  # pragma: no cover - private-API drift
        # assume not-yet-up: at the CLI call site that is true, and a wrong
        # guess surfaces as initialize()'s own "must be called before any
        # backend" error instead of silently skipping multi-host init
        backends_up = False
    if backends_up:
        # initialize() would raise; just report what we're running under
        return jax.process_count() > 1
    # Cross-process collectives on the CPU backend need an explicit
    # implementation (default 'none' fails at execute time) — this is the
    # multi-process CPU test mode, the analog of the reference's 2-process
    # Gloo CI (reference tests/test_algos/test_algos.py:16-52; same Gloo!).
    # The knob only affects the CPU backend, so set it unconditionally.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # pragma: no cover - knob renamed upstream
        pass
    # an explicitly requested multi-host run must not silently degrade to N
    # independent single-host trainings racing on the same run dir — let
    # coordinator failures propagate
    jax.distributed.initialize(coordinator_address, num_processes, process_id)
    return jax.process_count() > 1


class Fabric:
    """Mesh-owning runtime handed to every algorithm entrypoint as ``fabric``."""

    def __init__(
        self,
        devices: Any = "auto",
        num_nodes: int = 1,
        strategy: str = "auto",
        accelerator: str = "auto",
        precision: str = "32-true",
        callbacks: Optional[Sequence[Any]] = None,
        data_axis: str = "data",
        prng_impl: Optional[str] = "rbg",
        model_axis: int = 1,
        shard_min_bytes: Optional[int] = None,
        shard_overrides: Optional[Dict[str, Any]] = None,
    ):
        if prng_impl:
            # rbg (default): XLA-native random bits, markedly cheaper than
            # threefry on TPU (pre-drawn scan/imagination noise is ~0.4 ms of
            # the DV3 step under threefry). Still deterministic per seed; set
            # fabric.prng_impl=threefry for jax's default counter-based keys.
            # NOTE: this is process-global jax config — when two Fabrics with
            # different impls coexist in one process, the last constructed
            # wins for subsequently created keys.
            prng_impl = {"threefry": "threefry2x32"}.get(prng_impl, prng_impl)
            if prng_impl not in ("rbg", "threefry2x32", "unsafe_rbg"):
                raise ValueError(
                    f"Unknown fabric.prng_impl {prng_impl!r}; expected one of "
                    "'rbg', 'threefry' (threefry2x32), 'unsafe_rbg'"
                )
            if prng_impl != "threefry2x32" and not hasattr(jax, "shard_map"):
                # pre-graduation jax ships an XLA whose SPMD partitioner hard
                # CHECK-fails (`!IsManual()`) on the RngBitGenerator op that
                # rbg keys lower to inside shard_map's manual regions; on such
                # versions every multi-device train step would abort the
                # process. Counter-based threefry partitions fine everywhere.
                import warnings

                warnings.warn(
                    f"fabric.prng_impl={prng_impl!r} is not usable inside "
                    "shard_map on this jax version (XLA SPMD partitioner "
                    "crashes on manual RngBitGenerator); falling back to "
                    "'threefry2x32'",
                    UserWarning,
                )
                prng_impl = "threefry2x32"
            jax.config.update("jax_default_prng_impl", prng_impl)
        self.strategy = strategy or "auto"
        self.accelerator = accelerator or "auto"
        self.precision = precision or "32-true"
        self.callbacks = list(callbacks or [])
        self.num_nodes = num_nodes
        self._devices = _select_devices(devices, self.accelerator)
        self.data_axis = data_axis
        self.model_axis = int(model_axis) if model_axis is not None else 1
        if self.model_axis < 1:
            raise ValueError(f"parallel.model_axis must be >= 1, got {model_axis}")
        self.shard_min_bytes = (
            int(shard_min_bytes)
            if shard_min_bytes is not None
            else _shard.DEFAULT_MIN_SHARD_BYTES
        )
        self.shard_overrides = dict(shard_overrides) if shard_overrides else None
        if self.model_axis > 1:
            # {'data': -1, 'model': N} — the GSPMD parameter-sharding mesh.
            # make_mesh raises when N does not divide the device count.
            self.mesh = _mesh.make_mesh(
                {data_axis: -1, _mesh.MODEL_AXIS: self.model_axis}, self._devices
            )
        else:
            # model_axis=1 keeps the 1-D mesh byte-identical to the pure
            # data-parallel runtime: same jaxpr, same reduction order, so
            # sharded-vs-replicated bitwise parity holds by construction.
            self.mesh = Mesh(np.asarray(self._devices), (data_axis,))
        self._launched = False

    # ------------------------------------------------------------------
    # topology
    # ------------------------------------------------------------------

    @property
    def world_size(self) -> int:
        """Number of devices in the mesh (reference: number of DDP ranks)."""
        return len(self._devices)

    @property
    def on_accelerator(self) -> bool:
        """True when the mesh runs on an accelerator (acting then mirrors
        parameters to the CPU host — see utils/host.py)."""
        return self.mesh.devices.flat[0].platform != "cpu"

    @property
    def global_rank(self) -> int:
        """Process index — host-side identity for logging/checkpointing."""
        return jax.process_index()

    @property
    def local_rank(self) -> int:
        return 0

    @property
    def node_rank(self) -> int:
        return jax.process_index()

    @property
    def is_global_zero(self) -> bool:
        return jax.process_index() == 0

    @property
    def device(self) -> jax.Device:
        return self._devices[0]

    @property
    def local_devices(self) -> List[jax.Device]:
        return [d for d in self._devices if d.process_index == jax.process_index()]

    # ------------------------------------------------------------------
    # precision
    # ------------------------------------------------------------------

    @property
    def compute_dtype(self):
        """None for f32, bf16 under mixed precision — params stay f32,
        activations bf16 (the TPU-native analog of fabric's "bf16-mixed")."""
        return compute_dtype_from_precision(self.precision)

    @property
    def param_dtype(self):
        return jnp.float32

    # ------------------------------------------------------------------
    # shardings
    # ------------------------------------------------------------------

    def sharding(self, *spec: Any) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    @property
    def data_sharding(self) -> NamedSharding:
        """Leading axis split over the mesh's data axis."""
        return NamedSharding(self.mesh, P(self.data_axis))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def shard_data(self, tree: Any) -> Any:
        """Host→HBM: place a pytree with leading-axis data-parallel sharding."""
        return jax.device_put(tree, self.data_sharding)

    def to_device(self, tree: Any) -> Any:
        """Host→HBM replicated placement."""
        return jax.device_put(tree, self.replicated)

    # ------------------------------------------------------------------
    # parameter sharding (the {'data','model'} mesh)
    # ------------------------------------------------------------------

    @property
    def model_axis_size(self) -> int:
        """Size of the ``'model'`` parameter-sharding axis (1 = replicated)."""
        return self.model_axis

    @property
    def data_parallel_size(self) -> int:
        """Size of the data axis — the gradient-pmean world. Equals
        ``world_size`` unless ``model_axis`` carves devices out of it."""
        return int(self.mesh.shape[self.data_axis])

    @property
    def auto_axes(self):
        """Mesh axes left to the GSPMD partitioner inside ``shard_map``
        bodies (empty ⇒ the fully-manual 1-D data-parallel path)."""
        if self.model_axis > 1:
            return frozenset({_mesh.MODEL_AXIS})
        return frozenset()

    def shard_plan(self, tree: Any) -> Optional["_shard.ShardingPlan"]:
        """Spec-assign ``tree``'s leaves over the ``'model'`` axis.

        Returns ``None`` when ``model_axis`` is 1 so call sites can branch
        ``plan is None`` onto the byte-identical replicated path. Honors the
        ``parallel.shard_min_bytes`` / ``parallel.shard_overrides`` knobs.
        """
        if self.model_axis <= 1:
            return None
        return _shard.make_plan(
            tree,
            self.mesh,
            min_shard_bytes=self.shard_min_bytes,
            overrides=self.shard_overrides,
        )

    # ------------------------------------------------------------------
    # launch & module setup (reference-API parity shims)
    # ------------------------------------------------------------------

    def launch(self, fn: Callable, *args: Any, **kwargs: Any) -> Any:
        """Run the entrypoint. No process spawning: SPMD jit covers all local
        devices, and multi-host launch is external (one process per host via
        ``jax.distributed``), so this just validates topology and calls in."""
        self._launched = True
        if self.num_nodes > 1 and jax.process_count() == 1:
            # too late to bring up jax.distributed here (backends are already
            # initialized by the device query in __init__) — the CLI calls
            # init_distributed() before constructing Fabric
            warnings.warn(
                f"fabric.num_nodes={self.num_nodes} but jax.distributed is not initialized; "
                "running single-host (call sheeprl_tpu.fabric.init_distributed() before "
                "creating Fabric, or launch via the CLI which does)"
            )
        # Eager host-side work in the entrypoint (flax param init, PRNG key
        # math, staging) defaults to the local CPU: every op traced eagerly
        # on an accelerator is its own XLA program — over a remote-attached
        # TPU that is a compile + round trip *per op*. Mesh computation is
        # unaffected: the train programs carry explicit shardings/meshes and
        # their inputs are committed with device_put.
        try:
            jax.config.update("jax_default_device", jax.devices("cpu")[0])
        except RuntimeError:  # pragma: no cover - no cpu backend
            pass
        return fn(self, *args, **kwargs)

    def setup_module(self, module: Any) -> Any:
        """Parity shim: flax params are plain pytrees; DP is expressed via
        shardings at jit boundaries, not module wrappers."""
        return module

    def setup_optimizers(self, *optimizers: Any):
        return optimizers if len(optimizers) > 1 else optimizers[0]

    # ------------------------------------------------------------------
    # host-level collectives (cross-process; in-step collectives are XLA's).
    # Every multi-process branch runs inside a measured comms span
    # (obs/dist/comms.py): payload bytes, wall time, and achieved wire GB/s
    # land in telemetry.json as comms_ms/comms_bytes + a per-kind breakdown
    # — the instrumentation ROADMAP item 2's measured scaling study needs.
    # ------------------------------------------------------------------

    def barrier(self, name: str = "") -> None:
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            from sheeprl_tpu.obs.dist.comms import collective_span

            with collective_span("barrier"):
                multihost_utils.sync_global_devices(name or "fabric-barrier")

    def all_gather(self, tree: Any) -> Any:
        """Gather a host-side pytree across processes → leaves with a new
        leading process axis. Single-process: adds the axis (world view)."""
        if jax.process_count() == 1:
            return jax.tree_util.tree_map(lambda x: np.asarray(x)[None], tree)
        from jax.experimental import multihost_utils

        from sheeprl_tpu.obs.counters import tree_nbytes
        from sheeprl_tpu.obs.dist.comms import collective_span

        tree = jax.tree_util.tree_map(np.asarray, tree)
        with collective_span(
            "all_gather", payload_bytes=tree_nbytes(tree) * jax.process_count()
        ):
            return jax.tree_util.tree_map(
                lambda x: np.asarray(multihost_utils.process_allgather(x)), tree
            )

    def broadcast(self, tree: Any, src: int = 0) -> Any:
        if jax.process_count() == 1:
            return tree
        from jax.experimental import multihost_utils

        from sheeprl_tpu.obs.counters import tree_nbytes
        from sheeprl_tpu.obs.dist.comms import collective_span

        tree = jax.tree_util.tree_map(np.asarray, tree)
        with collective_span("broadcast", payload_bytes=tree_nbytes(tree)):
            return jax.tree_util.tree_map(
                lambda x: np.asarray(multihost_utils.broadcast_one_to_all(x)), tree
            )

    def all_reduce(self, tree: Any, op: str = "sum") -> Any:
        """Sum (or mean) a host-side float pytree across processes with a
        REAL on-the-wire all-reduce: leaves are committed to the world mesh
        sharded over ``data`` and reduced by one jitted cross-process
        program — the same collective XLA inserts for gradient syncs, so
        timing this call measures the actual link (``tools/bench_comms.py``
        times the 33 MB gradient payload through exactly this path).
        Single-process: identity for ``sum``/``mean`` over one participant.
        """
        if op not in ("sum", "mean"):
            raise ValueError(f"fabric.all_reduce supports op='sum'|'mean', got {op!r}")
        if jax.process_count() == 1:
            return jax.tree_util.tree_map(np.asarray, tree)
        import jax.numpy as jnp
        from jax.experimental import multihost_utils

        from sheeprl_tpu.obs.counters import tree_nbytes
        from sheeprl_tpu.obs.dist.comms import collective_span

        n_local = max(len(self.local_devices), 1)
        denom = np.float32(n_local * (jax.process_count() if op == "mean" else 1))
        reduce_fn = getattr(self, "_allreduce_fn", None)
        if reduce_fn is None:
            # cached so repeated calls (the bench's timed repeats) hit the
            # jit cache instead of recompiling per call
            reduce_fn = jax.jit(
                lambda g, d: jnp.sum(g, axis=0) / d,
                out_shardings=NamedSharding(self.mesh, P()),
            )
            self._allreduce_fn = reduce_fn

        def _reduce_one(x: Any) -> np.ndarray:
            x = np.asarray(x)  # plain float/int/list leaves are fine
            if x.dtype.kind != "f":
                x = x.astype(np.float32)
            # every local device contributes one copy of this process's
            # leaf; the global sum therefore counts each process n_local
            # times — divided back out through `denom`
            local = np.broadcast_to(x[None], (n_local, *x.shape))
            garr = multihost_utils.host_local_array_to_global_array(
                local, self.mesh, P(self.data_axis)
            )
            out = reduce_fn(garr, denom)
            return np.asarray(jax.device_get(out.addressable_data(0)))

        payload = tree_nbytes(jax.tree_util.tree_map(np.asarray, tree))
        with collective_span("all_reduce", payload_bytes=payload):
            return jax.tree_util.tree_map(_reduce_one, tree)

    # ------------------------------------------------------------------
    # checkpointing (reference fabric.save/load → Orbax pytree checkpoint)
    # ------------------------------------------------------------------

    def save(self, path: str, state: Dict[str, Any]) -> None:
        """Checkpoint a state pytree. EVERY process must call this: Orbax's
        Checkpointer.save runs its own cross-process sync barriers
        (multihost.sync_global_processes) even for host-local numpy state —
        gating the call to one process deadlocks the world at save_start.
        For replicated (non-sharded) values only the primary host writes
        bytes; the final barrier below keeps any immediate reader from
        racing the atomic rename (exercised end-to-end by
        tests/test_runtime/distributed_worker.py)."""
        import orbax.checkpoint as ocp

        path = os.path.abspath(path)
        state = jax.device_get(state)
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(path, state, force=True)
        self.barrier("fabric-save")  # no-op single-process

    def load(self, path: str, state: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Restore a checkpoint pytree (reference fabric.load semantics).

        Manifest-format checkpoints (the ``sheeprl_tpu.ckpt`` subsystem's
        atomic npz layout) are read with per-array checksum verification;
        legacy orbax directories restore as before. With ``state`` given,
        the raw restore is conformed to its structure (NamedTuple optimizer
        states rebuilt, extra on-disk keys like the optional replay-buffer
        snapshot kept raw at top level)."""
        from sheeprl_tpu.utils.utils import conform_pytree, migrate_legacy_checkpoint

        path = os.path.abspath(path)
        from sheeprl_tpu.ckpt.resume import is_manifest_checkpoint, read_checkpoint

        if is_manifest_checkpoint(path):
            restored = read_checkpoint(path, rank=self.global_rank)
        else:
            import orbax.checkpoint as ocp

            with ocp.PyTreeCheckpointer() as ckptr:
                restored = ckptr.restore(path)
        if state is not None:
            restored = migrate_legacy_checkpoint(state, restored)
            out = conform_pytree(state, restored)
            if isinstance(restored, dict):
                for k in restored:
                    if k not in out:
                        out[k] = restored[k]
            return out
        return restored

    # ------------------------------------------------------------------
    # callbacks (reference fabric.call → utils/callback.py)
    # ------------------------------------------------------------------

    def call(self, hook_name: str, **kwargs: Any) -> None:
        for cb in self.callbacks:
            hook = getattr(cb, hook_name, None)
            if callable(hook):
                hook(fabric=self, **kwargs)

    # ------------------------------------------------------------------
    # misc parity helpers
    # ------------------------------------------------------------------

    def seed_everything(self, seed: int) -> jax.Array:
        """Seed numpy/python and return the root jax PRNG key."""
        import random

        random.seed(seed)
        np.random.seed(seed)
        return jax.random.PRNGKey(seed)

    def print(self, *args: Any, **kwargs: Any) -> None:
        if self.is_global_zero:
            print(*args, **kwargs)
